"""Flight recorder (``srnn_tpu/telemetry/flightrec.py`` + the ``health=``
device carry): the forensic layer for the paper's pathologies.

Four layers, mirroring ISSUE 4's acceptance criteria:

  * carry parity: ``health=True`` leaves the evolved state BIT-IDENTICAL
    on every evolve path, and the device sentinels match a NumPy recount
    of the same weights (unsharded, multi, and sharded-global).
  * units: ring bounds/ordering, watchdog trip rules, triage-bundle
    layout, the ``StallSentinel`` dead-man's switch, and the
    ``ChunkDriver`` stall deadline (a hung finisher becomes a NAMED
    ``StallError`` carrying a bundle path).
  * end-to-end: NaNs injected into a mega-soup population mid-run trip
    the watchdog, the bundle renders via ``report --triage``, and
    ``--resume <bundle_dir>`` replays from its snapshot.
"""

import glob
import json
import math
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology
from srnn_tpu.soup import SoupConfig, evolve, seed
from srnn_tpu.telemetry import report
from srnn_tpu.telemetry.device import (HEALTH_BUCKET_LO, HEALTH_BUCKET_STEP,
                                       N_HEALTH_BUCKETS, probe_health)
from srnn_tpu.telemetry.flightrec import (FlightRecorder, StallSentinel,
                                          Watchdog, combined_health_summary,
                                          health_summary,
                                          write_triage_bundle)
from srnn_tpu.utils.pipeline import ChunkDriver, StallError


def _full_cfg(layout):
    return SoupConfig(topo=Topology("weightwise"), size=12,
                      attacking_rate=0.3, learn_from_rate=0.2,
                      learn_from_severity=1, train=1,
                      remove_divergent=True, remove_zero=True, layout=layout)


def _np_health(w, epsilon):
    """NumPy recount of one generation's sentinels from a (N, P) matrix."""
    norm = np.abs(np.asarray(w, np.float32)).max(axis=-1)
    finite = np.isfinite(norm)
    nonfinite = int((~finite).sum())
    zero = int((finite & (norm <= epsilon)).sum())
    safe = np.where(finite & (norm > 0), norm,
                    np.float32(2.0) ** HEALTH_BUCKET_LO)
    b = np.clip((np.floor(np.log2(safe)).astype(np.int64) - HEALTH_BUCKET_LO)
                // HEALTH_BUCKET_STEP, 0, N_HEALTH_BUCKETS - 1)
    hist = np.bincount(b[finite], minlength=N_HEALTH_BUCKETS)
    fin = norm[finite]
    return nonfinite, zero, hist, fin


# ---------------------------------------------------------------------------
# device carry: parity + recount
# ---------------------------------------------------------------------------


def test_probe_health_counts_crafted_population():
    """Known pathologies land in the right sentinel: NaN/Inf rows are
    nonfinite, exact-zero and sub-epsilon rows are zero-collapsed, finite
    rows fill the log2 sketch and the extrema."""
    w = jnp.array([[np.nan, 1.0, 0.5],     # nonfinite (NaN)
                   [np.inf, 0.0, 0.0],     # nonfinite (Inf)
                   [0.0, 0.0, 0.0],        # zero-collapsed (exactly)
                   [1e-5, -1e-5, 0.0],     # zero-collapsed (<= epsilon)
                   [0.5, -0.25, 0.125],    # healthy
                   [4.0, -2.0, 1.0]],      # healthy
                  jnp.float32)
    h = probe_health(w, -1, 1e-4)
    assert int(h.checks) == 1
    assert int(h.nonfinite) == int(h.nonfinite_peak) == 2
    assert int(h.zero) == int(h.zero_peak) == 2
    assert float(h.norm_min) == pytest.approx(0.0)  # the zero row
    assert float(h.norm_max) == pytest.approx(4.0)
    assert int(h.norm_hist.sum()) == 4  # finite rows only
    nonf, zero, hist, _fin = _np_health(w, 1e-4)
    assert (nonf, zero) == (2, 2)
    np.testing.assert_array_equal(np.asarray(h.norm_hist), hist)

    s = health_summary(h, 6)
    assert s["nan_frac"] == pytest.approx(2 / 6)
    assert s["zero_frac"] == pytest.approx(2 / 6)
    assert s["norm_max"] == pytest.approx(4.0)
    # p50 falls in the bucket holding the finite norms' median
    assert s["norm_p50"] > 0


@pytest.mark.parametrize("layout", ["rowmajor", "popmajor"])
def test_health_carry_parity_and_recount(layout):
    """``health=True`` evolution is bit-identical to plain, composes with
    ``metrics=``/``record=``, and the carry matches a NumPy recount of the
    recorded per-generation weight stream."""
    cfg = _full_cfg(layout)
    st = seed(cfg, jax.random.key(3))
    plain = evolve(cfg, st, generations=4)
    sentineled, h = evolve(cfg, st, generations=4, health=True)
    np.testing.assert_array_equal(np.asarray(plain.weights),
                                  np.asarray(sentineled.weights))
    np.testing.assert_array_equal(np.asarray(plain.uids),
                                  np.asarray(sentineled.uids))
    assert int(h.checks) == 4

    # recount every sentinel from the recorded post-step weights
    _f, (_ev, w_stream, _u) = evolve(cfg, st, generations=4, record=True)
    w_stream = np.asarray(w_stream)          # (G, N, P)
    per_gen = [_np_health(w, cfg.epsilon) for w in w_stream]
    assert int(h.nonfinite) == per_gen[-1][0]       # end-of-window
    assert int(h.zero) == per_gen[-1][1]
    assert int(h.nonfinite_peak) == max(g[0] for g in per_gen)
    assert int(h.zero_peak) == max(g[1] for g in per_gen)
    np.testing.assert_array_equal(np.asarray(h.norm_hist),
                                  sum(g[2] for g in per_gen))
    fins = np.concatenate([g[3] for g in per_gen])
    assert float(h.norm_min) == pytest.approx(float(fins.min()), rel=1e-6)
    assert float(h.norm_max) == pytest.approx(float(fins.max()), rel=1e-6)

    # metrics + health compose; the metrics carry is unchanged by health
    _f2, m2, h2 = evolve(cfg, st, generations=4, metrics=True, health=True)
    _f3, m3 = evolve(cfg, st, generations=4, metrics=True)
    np.testing.assert_array_equal(np.asarray(m2.actions),
                                  np.asarray(m3.actions))
    np.testing.assert_array_equal(np.asarray(h2.norm_hist),
                                  np.asarray(h.norm_hist))


def test_multi_health_parity_and_probe_agreement():
    from srnn_tpu.multisoup import MultiSoupConfig, evolve_multi, seed_multi

    mc = MultiSoupConfig(
        topos=(Topology("weightwise"), Topology("aggregating", aggregates=4)),
        sizes=(6, 6), attacking_rate=0.4, learn_from_rate=0.3,
        learn_from_severity=1, train=1, remove_divergent=True,
        remove_zero=True)
    st = seed_multi(mc, jax.random.key(0))
    plain = evolve_multi(mc, st, generations=3)
    sentineled, ms, hs = evolve_multi(mc, st, generations=3, metrics=True,
                                      health=True)
    assert len(hs) == len(mc.topos) == len(ms)
    for t, (wa, wb) in enumerate(zip(plain.weights, sentineled.weights)):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
        # each type's end-of-window counts match a recount of ITS weights
        nonf, zero, _hist, _fin = _np_health(wb, mc.epsilon)
        assert int(hs[t].nonfinite) == nonf
        assert int(hs[t].zero) == zero
        assert int(hs[t].checks) == 3

    combined = combined_health_summary(
        [health_summary(h, n) for h, n in zip(hs, mc.sizes)])
    assert combined["n_particles"] == sum(mc.sizes)
    assert 0 <= combined["zero_frac"] <= 1


def test_sharded_health_matches_unsharded_and_recount(mesh):
    """The sharded scan's psum'd health carry reports GLOBAL fractions:
    equal to the single-device carry's and to a NumPy recount of the
    sharded final population."""
    from srnn_tpu.parallel import make_sharded_state
    from srnn_tpu.parallel.sharded_soup import sharded_evolve

    cfg = SoupConfig(topo=Topology("weightwise"), size=16,
                     attacking_rate=0.4, remove_divergent=True,
                     remove_zero=True, layout="popmajor")
    sst = make_sharded_state(cfg, mesh, jax.random.key(1))
    sh, h_sh = sharded_evolve(cfg, mesh, sst, generations=4, health=True)
    un, h_un = evolve(cfg, seed(cfg, jax.random.key(1)), generations=4,
                      health=True)
    for field in ("checks", "nonfinite", "zero"):
        assert int(getattr(h_sh, field)) == int(getattr(h_un, field))
    # window peaks: the psum of per-shard maxima upper-bounds the true
    # global per-generation peak and never undercounts the end state
    assert int(h_sh.nonfinite_peak) >= int(h_un.nonfinite)
    assert int(h_sh.zero_peak) >= int(h_un.zero)
    np.testing.assert_array_equal(np.asarray(h_sh.norm_hist),
                                  np.asarray(h_un.norm_hist))
    np.testing.assert_allclose(float(h_sh.norm_min), float(h_un.norm_min),
                               rtol=1e-5)
    np.testing.assert_allclose(float(h_sh.norm_max), float(h_un.norm_max),
                               rtol=1e-5)
    # global end-of-window counts == NumPy recount of the sharded result
    nonf, zero, _hist, _fin = _np_health(np.asarray(sh.weights), cfg.epsilon)
    assert int(h_sh.nonfinite) == nonf
    assert int(h_sh.zero) == zero


# ---------------------------------------------------------------------------
# ring + watchdog units
# ---------------------------------------------------------------------------


def test_ring_bounds_orders_and_dumps(tmp_path):
    ring = FlightRecorder(capacity=4)
    for i in range(7):
        ring.record({"gen": i})
    rows = ring.rows()
    assert len(rows) == len(ring) == 4
    assert [r["gen"] for r in rows] == [3, 4, 5, 6]   # oldest dropped
    assert [r["seq"] for r in rows] == [3, 4, 5, 6]   # monotone stamps
    assert ring.tail(2) == rows[-2:]
    path = ring.write(str(tmp_path / "ring.jsonl"))
    loaded = [json.loads(l) for l in open(path)]
    assert [r["gen"] for r in loaded] == [3, 4, 5, 6]


def test_watchdog_rules():
    ring = FlightRecorder()
    wd = Watchdog(ring, nan_frac=0.02, zero_frac=0.9, respawn_frac=0.25,
                  gens_regress=0.5, min_history=3, profile_trips=False)
    assert wd.check({"health": {"nan_frac": 0.01, "zero_frac": 0.1}}) == []
    assert wd.check({"health": {"nan_frac": 0.5}}) == ["nan_frac"]
    assert wd.check({"health": {"zero_frac": 0.95}}) == ["zero_frac"]
    assert wd.check({"respawns": 60, "particle_gens": 100}) \
        == ["respawn_frac"]
    assert wd.check({"health": {"nan_frac": 0.5, "zero_frac": 0.95}}) \
        == ["nan_frac", "zero_frac"]

    # gens_regress needs min_history prior rows, then trips on a fall
    # below (1 - F) of the ring median
    slow = {"gens_per_sec": 40.0}
    assert wd.check(slow) == []          # no history yet
    for _ in range(3):
        ring.record({"gens_per_sec": 100.0})
    assert wd.check(slow) == ["gens_regress"]
    assert wd.check({"gens_per_sec": 60.0}) == []  # above the cut

    # disabled rules (None / <= 0) never trip
    off = Watchdog(ring, nan_frac=None, zero_frac=0.0, respawn_frac=-1,
                   gens_regress=0.0, profile_trips=False)
    assert off.check({"health": {"nan_frac": 1.0, "zero_frac": 1.0},
                      "respawns": 100, "particle_gens": 100,
                      "gens_per_sec": 1.0}) == []


def test_triage_bundle_layout_and_report_roundtrip(tmp_path, capsys):
    """A bundle written with a population snapshot restores, renders, and
    rate-limits at ``max_bundles``."""
    from srnn_tpu.experiment import restore_checkpoint, save_checkpoint

    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    with open(os.path.join(run_dir, "config.json"), "w") as f:
        json.dump({"size": 12, "layout": "rowmajor"}, f)

    cfg = _full_cfg("rowmajor")
    state = evolve(cfg, seed(cfg, jax.random.key(0)), generations=2)
    ring = FlightRecorder()
    row = {"gen": 2, "gens_per_sec": 50.0,
           "health": {"nan_frac": 0.5, "zero_frac": 0.0}}
    ring.record(row)
    wd = Watchdog(ring, max_bundles=1, profile_trips=False)
    reasons = wd.check(row)
    assert reasons == ["nan_frac"]
    bundle = wd.trip(reasons, row, run_dir=run_dir, snapshot_state=state,
                     save_fn=save_checkpoint, generation=2)
    assert bundle and os.path.dirname(bundle) == run_dir

    trip = json.load(open(os.path.join(bundle, "trip.json")))
    assert trip["reasons"] == ["nan_frac"]
    assert trip["generation"] == 2
    assert trip["thresholds"]["nan_frac"] == 0.02
    assert os.path.exists(os.path.join(bundle, "ring.jsonl"))
    assert os.path.exists(os.path.join(bundle, "config.json"))

    # the snapshot IS a resumable checkpoint at the trip generation
    restored = restore_checkpoint(os.path.join(bundle, "ckpt-gen00000002"))
    np.testing.assert_array_equal(np.asarray(restored.weights),
                                  np.asarray(state.weights))

    # report --triage renders it (text + json)
    assert report.main(["--triage", bundle]) == 0
    out = capsys.readouterr().out
    assert "tripped: nan_frac at generation 2" in out
    assert "ckpt-gen00000002" in out
    assert "resume with" in out
    s = report.summarize_triage(bundle)
    assert s["trip"]["reasons"] == ["nan_frac"]
    assert s["snapshot"]["kind"] == "soup"
    assert s["health_trajectory"][-1]["nan_frac"] == 0.5

    # quota spent: further trips record but write no bundle
    assert wd.trip(["nan_frac"], row, run_dir=run_dir) is None
    assert wd.trips == 2 and len(wd.bundles) == 1


def test_host_only_bundle_renders_without_snapshot(tmp_path, capsys):
    """A stall bundle has no population snapshot (the device is presumed
    hung); the renderer must say so instead of crashing."""
    run_dir = str(tmp_path)
    bundle = write_triage_bundle(run_dir, ["stall"], {"gen": 10},
                                 recorder=FlightRecorder(),
                                 thresholds={"stall_timeout_s": 5.0})
    assert report.main(["--triage", bundle]) == 0
    out = capsys.readouterr().out
    assert "stall" in out
    assert "host-only bundle" in out


# ---------------------------------------------------------------------------
# dead-man's switch + chunk-driver stall deadline
# ---------------------------------------------------------------------------


def test_stall_sentinel_fires_once_after_deadline():
    fired = []
    s = StallSentinel(0.15, lambda mark, waited: fired.append((mark, waited)))
    try:
        s.mark("step-1")
        time.sleep(0.05)
        assert not s.fired          # marks keep resetting the deadline
        time.sleep(0.4)
        assert s.fired
        assert len(fired) == 1
        assert fired[0][0] == "step-1"
        assert fired[0][1] >= 0.15
    finally:
        s.stop()


def test_stall_sentinel_stop_disarms():
    fired = []
    s = StallSentinel(0.2, lambda *_: fired.append(1))
    s.stop()
    time.sleep(0.4)
    assert not fired and not s.fired


def test_chunk_driver_stall_raises_named_error_with_bundle():
    drv = ChunkDriver(depth=0, stall_timeout_s=0.2,
                      on_stall=lambda timeout_s: f"/bundles/t{timeout_s}")
    release = threading.Event()
    with pytest.raises(StallError) as ei:
        drv.step(lambda: release.wait(10))
    assert ei.value.bundle == "/bundles/t0.2"
    assert "stall deadline" in str(ei.value)
    release.set()  # unwedge the watched daemon thread

    # a finisher that FAILS inside the deadline re-raises its own error
    def boom():
        raise ValueError("finisher bug")

    with pytest.raises(ValueError, match="finisher bug"):
        drv.step(boom)

    # fast finishers pass through; the deferred-depth contract holds
    done = []
    drv2 = ChunkDriver(depth=1, stall_timeout_s=5.0)
    drv2.step(lambda: done.append(1))
    assert done == []               # deferred behind depth=1
    drv2.drain()
    assert done == [1]


def test_chunk_driver_no_deadline_runs_inline():
    """stall_timeout_s=0 (the default) must not touch threads at all."""
    tids = []
    drv = ChunkDriver(depth=0)
    drv.step(lambda: tids.append(threading.get_ident()))
    assert tids == [threading.get_ident()]


# ---------------------------------------------------------------------------
# end-to-end: injected NaNs -> trip -> bundle -> report -> resume
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_watchdog_e2e_nan_injection_bundle_resume(tmp_path, monkeypatch,
                                                  capsys):
    """The acceptance scenario: NaNs injected into the whole population at
    chunk 2 of a smoke mega-soup run trip the watchdog (as a respawn
    storm: the soup cleans the casualties within the chunk), the run
    still completes, the bundle renders via ``report --triage``, and
    ``--resume <bundle_dir>`` replays from its snapshot to the end."""
    import srnn_tpu.setups.mega_soup as mega_soup
    from srnn_tpu.experiment import restore_checkpoint
    from srnn_tpu.setups import REGISTRY

    real = mega_soup.evolve_donated
    calls = {"n": 0}

    def poisoned(cfg, st, **kw):
        calls["n"] += 1
        if calls["n"] == 2:  # chunk 2's input population: all-NaN
            st = st._replace(weights=jnp.full_like(st.weights, jnp.nan))
        return real(cfg, st, **kw)

    monkeypatch.setattr(mega_soup, "evolve_donated", poisoned)
    d = REGISTRY["mega_soup"](["--smoke", "--root", str(tmp_path / "run")])

    bundles = sorted(glob.glob(os.path.join(d, "triage-gen*")))
    assert len(bundles) == 1, f"expected exactly one trip, got {bundles}"
    bundle = bundles[0]
    trip = json.load(open(os.path.join(bundle, "trip.json")))
    assert "respawn_frac" in trip["reasons"]
    assert trip["generation"] == 4              # end of the poisoned chunk
    assert trip["row"]["respawns"] >= 64        # the whole population died
    assert math.isfinite(trip["row"]["health"]["nan_frac"])
    # the ring went into the bundle, and the run dir logged the trip
    assert os.path.exists(os.path.join(bundle, "ring.jsonl"))
    events = [json.loads(l) for l in open(os.path.join(d, "events.jsonl"))]
    wd_rows = [r for r in events if r.get("kind") == "watchdog"]
    assert wd_rows and wd_rows[0]["bundle"] == bundle
    metrics = [r for r in events if "srnn_soup_watchdog_trips_total"
               in json.dumps(r)]
    assert metrics, "the trip counter must reach the metrics sink"

    # render
    assert report.main(["--triage", bundle]) == 0
    out = capsys.readouterr().out
    assert "respawn_frac" in out and "health trajectory" in out

    # resume FROM THE BUNDLE: its snapshot is generation 4 of 6
    snap = restore_checkpoint(os.path.join(bundle, "ckpt-gen00000004"))
    assert int(snap.time) == 4
    d_resumed = REGISTRY["mega_soup"](["--smoke", "--resume", bundle])
    assert d_resumed == bundle
    final = restore_checkpoint(os.path.join(bundle, "ckpt-gen00000006"))
    assert int(final.time) == 6


@pytest.mark.slow
def test_mega_soup_stall_deadline_names_failure_with_bundle(tmp_path,
                                                            monkeypatch):
    """A deliberately hung chunk finisher inside the real mega loop is
    converted by ``--stall-timeout-s`` into a named ``StallError``
    carrying a host-only bundle path (no snapshot: the device is presumed
    hung), instead of an opaque hang."""
    import srnn_tpu.setups.mega_soup as mega_soup
    from srnn_tpu.setups import REGISTRY
    from srnn_tpu.utils.pipeline import live_threads

    release = threading.Event()
    monkeypatch.setattr(mega_soup, "update_class_gauges",
                        lambda *a, **k: release.wait(60))
    try:
        with pytest.raises(StallError) as ei:
            # --max-restarts 0: this test wants the RAW StallError, not
            # the supervisor's recovery of it (tests/test_resilience.py
            # covers the supervised path)
            REGISTRY["mega_soup"](["--smoke", "--no-pipeline",
                                   "--stall-timeout-s", "1",
                                   "--max-restarts", "0",
                                   "--root", str(tmp_path / "run")])
        bundle = ei.value.bundle
        assert bundle and os.path.isdir(bundle)
        assert "stall deadline" in str(ei.value)
        trip = json.load(open(os.path.join(bundle, "trip.json")))
        assert trip["reasons"] == ["stall"]
        assert trip["thresholds"]["stall_timeout_s"] == 1.0
        assert "snapshot" not in trip          # host-only by design
        assert os.path.exists(os.path.join(bundle, "ring.jsonl"))
        assert os.path.exists(os.path.join(bundle, "metrics.json"))
    finally:
        release.set()  # unwedge the watched daemon thread
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and any(
            t.name == "srnn-chunk-finisher" for t in live_threads()):
        time.sleep(0.05)
    assert not [t for t in live_threads()
                if t.name == "srnn-chunk-finisher"]


def test_mega_soup_no_health_still_records_ring(tmp_path):
    """``--no-health`` drops the device sentinels but the flight recorder
    still rings (gens/sec, counts, respawn counters from the metrics
    carry) and the run completes with no health rows."""
    from srnn_tpu.setups import REGISTRY

    d = REGISTRY["mega_soup"](["--smoke", "--no-health",
                               "--root", str(tmp_path / "run")])
    events = [json.loads(l) for l in open(os.path.join(d, "events.jsonl"))]
    assert not glob.glob(os.path.join(d, "triage-gen*"))
    assert not any("srnn_soup_health_nan_frac" in json.dumps(r)
                   for r in events)
