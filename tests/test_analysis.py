"""srnnlint framework tests: deliberately-bad fixture snippets per pass
(each pass must FIRE on its seeded violation), the clean-repo gate (the
real repo yields zero unwaived findings), and the waiver machinery
(reasons required, stale waivers reported, matching suppresses)."""

import os
import textwrap

import pytest

from srnn_tpu.analysis import (AnalysisContext, run_analysis, select,
                               ALL_PASSES, PASSES_BY_ID)
from srnn_tpu.analysis.core import ERROR, WARNING, load_waivers

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_repo(tmp_path, files):
    """Write a mini repo ({rel: source}) and parse it into a context."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return AnalysisContext.from_root(str(tmp_path))


def run_pass(ctx, pass_id):
    return list(PASSES_BY_ID[pass_id].run(ctx))


def codes(findings):
    return sorted(f.code for f in findings)


# ---------------------------------------------------------------------------
# the clean-repo gate: zero unwaived findings on the real tree
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def repo_ctx():
    return AnalysisContext.from_root(REPO_ROOT)


@pytest.mark.parametrize("pass_id", [p.id for p in ALL_PASSES])
def test_repo_is_clean_per_pass(repo_ctx, pass_id):
    result = run_analysis(repo_ctx, select([pass_id]))
    assert not result.errors, "\n".join(f.render() for f in result.errors)


def test_cli_clean_run_exits_zero(capsys):
    from srnn_tpu.analysis.__main__ import main

    assert main([]) == 0
    out = capsys.readouterr().out
    assert "srnnlint:" in out and "0 error(s)" in out


def test_cli_json_and_list(capsys):
    import json

    from srnn_tpu.analysis.__main__ import main

    assert main(["--list"]) == 0
    listing = capsys.readouterr().out
    for p in ALL_PASSES:
        assert p.id in listing
    assert main(["--json", "--fast"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["exit_code"] == 0
    assert set(data["passes"]) == {p.id for p in ALL_PASSES if p.fast}


def test_cli_unknown_pass_is_usage_error(capsys):
    from srnn_tpu.analysis.__main__ import main

    assert main(["no-such-pass"]) == 2
    capsys.readouterr()


def test_shipped_baseline_is_clean_and_empty(repo_ctx):
    """The repo analyzes clean with an EMPTY waiver baseline (the
    .metered.lineage F010 waivers retired when aot.py started warming
    that spelling; a stale leftover line would be a W002 finding)."""
    result = run_analysis(repo_ctx, select(None))
    assert not result.findings, \
        "\n".join(f.render() for f in result.findings)
    assert not result.waived, "the shipped baseline should waive nothing"
    assert not result.unused_waivers


def test_walk_roots_shared_config(repo_ctx):
    """The one shared walk-root policy: no __pycache__, no graft shim,
    no benchmarks/tests scratch (fixture snippets would trip passes),
    repo-level surface present, and the scripts walk sees the watch
    scripts."""
    rels = [m.rel for m in repo_ctx.modules]
    assert not any("__pycache__" in r for r in rels)
    assert not any(r.endswith("__graft_entry__.py") for r in rels)
    assert not any(r.startswith(("benchmarks/", "tests/", "examples/"))
                   for r in rels)
    assert "srnn_tpu/soup.py" in rels
    assert "bench.py" in rels          # repo-level surface is walked
    pkg = [m.rel for m in repo_ctx.package_modules()]
    assert "bench.py" not in pkg       # ...but package view excludes it
    shell = [s.rel for s in repo_ctx.shell_files]
    assert "scripts/tpu_watch.sh" in shell
    assert "scripts/tpu_window.sh" in shell


# ---------------------------------------------------------------------------
# donation safety
# ---------------------------------------------------------------------------


def test_donation_use_after_donate(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/loop.py": """
        def loop(cfg, state):
            out = evolve_donated(cfg, state)
            census = state.weights.sum()
            state = out[0]
            return census
        """})
    found = run_pass(ctx, "donation-safety")
    assert codes(found) == ["D001"]
    assert found[0].line == 4


def test_donation_snapshot_after_donate(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/loop.py": """
        from .pipeline import snapshot

        def loop(cfg, mesh, state):
            out = sharded_evolve_donated(cfg, mesh, state)
            snap = snapshot(state)
            state = out[0]
            return snap
        """})
    found = run_pass(ctx, "donation-safety")
    assert codes(found) == ["D002"]


def test_donation_sanctioned_pattern_is_clean(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/loop.py": """
        from .pipeline import snapshot

        def loop(cfg, state, writer):
            for _ in range(10):
                snap = snapshot(state)           # BEFORE the donation
                out = evolve_donated(cfg, state)
                state = out[0]
                writer.submit(lambda: resolve(snap))
                census = state.weights.sum()     # rebound: fine
            return state, census
        """})
    assert run_pass(ctx, "donation-safety") == []


def test_donation_loop_carried_use(tmp_path):
    """Donated at the bottom of a loop body without rebinding: the next
    iteration's read at the top is the bug."""
    ctx = make_repo(tmp_path, {"srnn_tpu/loop.py": """
        def loop(cfg, state):
            for _ in range(10):
                census = state.weights.sum()
                evolve_donated(cfg, state)
            return census
        """})
    found = run_pass(ctx, "donation-safety")
    assert "D001" in codes(found)


def test_donation_alias_and_branch_merge(tmp_path):
    """The mega-loop idiom: a maybe-donating alias donates, a read after
    an if that rebinds on BOTH arms is clean, a read after an if that
    rebinds on only one arm fires."""
    ctx = make_repo(tmp_path, {"srnn_tpu/loop.py": """
        def ok(cfg, mesh, state, sharded):
            run = sharded_evolve_donated if sharded else sharded_evolve
            out = run(cfg, mesh, state)
            if sharded:
                state = out[0]
            else:
                state = out[0]
            return state.uids

        def bad(cfg, mesh, state, sharded):
            run = sharded_evolve_donated if sharded else sharded_evolve
            out = run(cfg, mesh, state)
            if sharded:
                state = out[0]
            return state.uids
        """})
    found = run_pass(ctx, "donation-safety")
    assert codes(found) == ["D001"]
    assert found[0].line == 16   # the read in bad(), not the one in ok()


def test_donation_sees_into_match_statements(tmp_path):
    """match/case bodies are part of the scope — a use-after-donate
    inside a case arm must not be a blind spot."""
    ctx = make_repo(tmp_path, {"srnn_tpu/loop.py": """
        def loop(cfg, state, mode):
            match mode:
                case "fast":
                    out = evolve_donated(cfg, state)
                    census = state.weights.sum()
                case _:
                    out = None
            return out
        """})
    assert codes(run_pass(ctx, "donation-safety")) == ["D001"]


def test_donation_alias_retired_on_rebind(tmp_path):
    """Rebinding an alias to a non-donating callee must stop treating its
    calls as donating — correct code stays clean."""
    ctx = make_repo(tmp_path, {"srnn_tpu/loop.py": """
        def loop(cfg, state, state2, owned):
            run = evolve_donated if owned else evolve
            out = run(cfg, state)
            state = out[0]
            run = evolve
            out2 = run(cfg, state2)
            return state2.weights.sum()
        """})
    assert run_pass(ctx, "donation-safety") == []


# ---------------------------------------------------------------------------
# flag parity
# ---------------------------------------------------------------------------


_SURFACE_TEMPLATE = """
    import jax

    def {fn}({head}generations=1, metrics=False, health=False,
             lineage=False, lineage_state=None, lineage_capacity={cap}):
        return 0

    {plain} = jax.jit({fn}, static_argnames=({statics}))
    {donated} = jax.jit({fn}, static_argnames=({statics}),
                        donate_argnums=(1,))
    """

_FULL_STATICS = ('"config", "generations", "metrics", "health", '
                 '"lineage", "lineage_capacity"')


def _surface_files(sharded_multi_src=None, cap="4096", statics=None):
    statics = statics or _FULL_STATICS
    files = {
        "srnn_tpu/soup.py": _SURFACE_TEMPLATE.format(
            fn="_evolve", head="config, state, record=False, ",
            cap="4096", plain="evolve", donated="evolve_donated",
            statics=statics + ', "record"'),
        "srnn_tpu/multisoup.py": _SURFACE_TEMPLATE.format(
            fn="_evolve_multi", head="config, state, ", cap=cap,
            plain="evolve_multi", donated="evolve_multi_donated",
            statics=statics),
        "srnn_tpu/parallel/sharded_soup.py": _SURFACE_TEMPLATE.format(
            fn="_sharded_evolve", head="config, mesh, state, ", cap="4096",
            plain="sharded_evolve", donated="sharded_evolve_donated",
            statics=statics + ', "mesh"'),
        "srnn_tpu/parallel/sharded_multisoup.py": sharded_multi_src
        or _SURFACE_TEMPLATE.format(
            fn="_sharded_evolve_multi", head="config, mesh, state, ",
            cap="4096", plain="sharded_evolve_multi",
            donated="sharded_evolve_multi_donated",
            statics=statics + ', "mesh"'),
        # the serve tenant-axis surfaces hold the same contract (PR 10)
        "srnn_tpu/serve/tenant.py": _SURFACE_TEMPLATE.format(
            fn="_evolve_stacked", head="config, states, record=False, ",
            cap="4096", plain="evolve_stacked",
            donated="evolve_stacked_donated",
            statics=statics + ', "record"') + _SURFACE_TEMPLATE.format(
            fn="_evolve_multi_stacked", head="config, states, ",
            cap="4096", plain="evolve_multi_stacked",
            donated="evolve_multi_stacked_donated", statics=statics),
        "srnn_tpu/utils/aot.py": _AOT_FIXTURE,
    }
    return files


_AOT_FIXTURE = """
    def _soup_entries(config, generations, donate):
        yield ("soup.evolve", None, (config,), {})
        yield ("soup.evolve.metered", None, (config,),
               {"generations": 1, "metrics": True})
        yield ("soup.evolve.metered.health", None, (config,),
               {"metrics": True, "health": True})

    def _multi_entries(config, generations, donate):
        yield ("multisoup.evolve_multi", None, (config,), {})
        yield ("multisoup.evolve_multi.metered", None, (config,),
               {"metrics": True})

    def _sharded_entries(config, mesh, generations, donate):
        yield ("parallel.sharded_evolve", None, (config,), {})
        yield ("parallel.sharded_evolve.metered", None, (config,),
               {"metrics": True})

    def _sharded_multi_entries(config, mesh, generations, donate):
        yield ("parallel.sharded_evolve_multi", None, (config,), {})
        yield ("parallel.sharded_evolve_multi.metered", None, (config,),
               {"metrics": True})

    def _stacked_entries(config, k, generations, donate):
        yield ("serve.evolve_stacked", None, (config,), {})
        yield ("serve.evolve_stacked.metered", None, (config,),
               {"metrics": True})

    def _stacked_multi_entries(config, k, generations, donate):
        yield ("serve.evolve_multi_stacked", None, (config,), {})
        yield ("serve.evolve_multi_stacked.metered", None, (config,),
               {"metrics": True})
    """


def test_flag_parity_clean_fixture(tmp_path):
    ctx = make_repo(tmp_path, _surface_files())
    assert [f for f in run_pass(ctx, "flag-parity")
            if f.severity == ERROR] == []


def test_flag_parity_missing_flag_on_one_surface(tmp_path):
    bad = """
        import jax

        def _sharded_evolve_multi(config, mesh, state, generations=1,
                                  metrics=False, lineage=False,
                                  lineage_state=None, lineage_capacity=4096):
            return 0

        sharded_evolve_multi = jax.jit(_sharded_evolve_multi,
            static_argnames=("config", "mesh", "generations", "metrics",
                             "lineage", "lineage_capacity"))
        sharded_evolve_multi_donated = jax.jit(_sharded_evolve_multi,
            static_argnames=("config", "mesh", "generations", "metrics",
                             "lineage", "lineage_capacity"),
            donate_argnums=(2,))
        """
    ctx = make_repo(tmp_path, _surface_files(sharded_multi_src=bad))
    found = [f for f in run_pass(ctx, "flag-parity") if f.code == "F001"]
    assert len(found) == 1
    assert "health" in found[0].message
    assert found[0].path == "srnn_tpu/parallel/sharded_multisoup.py"


def test_flag_parity_default_mismatch(tmp_path):
    ctx = make_repo(tmp_path, _surface_files(cap="2048"))
    found = [f for f in run_pass(ctx, "flag-parity") if f.code == "F002"]
    assert found and "lineage_capacity" in found[0].message


def test_flag_parity_static_argnames(tmp_path):
    slim = _FULL_STATICS.replace(', "lineage_capacity"', '') \
        + ', "lineage_state"'
    ctx = make_repo(tmp_path, _surface_files(statics=slim))
    found = run_pass(ctx, "flag-parity")
    assert "F003" in codes(found)   # lineage_capacity not static
    assert "F004" in codes(found)   # lineage_state wrongly static


def test_flag_parity_warmup_gap(tmp_path):
    files = _surface_files()
    files["srnn_tpu/setups/mega.py"] = """
        def loop(cfg, state, lineage_on):
            kw = {"generations": 5, "metrics": True}
            if lineage_on:
                kw["lineage"] = True
            out = evolve_donated(cfg, state, **kw)
            state = out[0]
            return state
        """
    ctx = make_repo(tmp_path, files)
    found = [f for f in run_pass(ctx, "flag-parity") if f.code == "F010"]
    assert len(found) == 1
    assert ".metered.lineage" in found[0].message
    assert found[0].path == "srnn_tpu/setups/mega.py"


def test_flag_parity_same_named_dicts_stay_scoped(tmp_path):
    """Two functions both calling their flag dict ``kw`` must resolve
    against their OWN definition — the module-wide table is only a
    fallback for helper parameters, never a shadow."""
    files = _surface_files()
    files["srnn_tpu/setups/mega.py"] = """
        def a(cfg, state):
            kw = {"metrics": True, "lineage": True}
            return evolve_donated(cfg, state, **kw)

        def b(cfg, state):
            kw = {"metrics": True}
            return evolve_donated(cfg, state, **kw)
        """
    ctx = make_repo(tmp_path, files)
    found = [f for f in run_pass(ctx, "flag-parity") if f.code == "F010"]
    # only a()'s dispatch reaches the unwarmed .metered.lineage combo;
    # b() resolving against a()'s dict would double-report (or, with the
    # definitions swapped, silently miss a()'s gap)
    assert len(found) == 1
    assert found[0].line == 4


def test_flag_parity_helper_param_falls_back_to_module(tmp_path):
    """The mega_multisoup idiom: a flag dict built in the outer loop and
    passed into a local helper as a parameter still resolves."""
    files = _surface_files()
    files["srnn_tpu/setups/mega.py"] = """
        def run(cfg, state, lineage_on):
            def _evolve(s, lkw):
                return evolve_multi_donated(cfg, s, metrics=True, **lkw)

            lkw = {"lineage": True} if lineage_on else {}
            return _evolve(state, lkw)
        """
    ctx = make_repo(tmp_path, files)
    found = [f for f in run_pass(ctx, "flag-parity") if f.code == "F010"]
    assert len(found) == 1 and ".metered.lineage" in found[0].message


def test_flag_parity_conditional_reassign_keeps_both_combos(tmp_path):
    """A branch-local ``kw = {...}`` re-init must not wipe the base
    combo: both the taken and untaken paths stay checked."""
    files = _surface_files()
    files["srnn_tpu/setups/mega.py"] = """
        def loop(cfg, state, lineage_on):
            kw = {"metrics": True}
            if lineage_on:
                kw = {"metrics": True, "lineage": True}
            return evolve_donated(cfg, state, **kw)
        """
    ctx = make_repo(tmp_path, files)
    found = [f for f in run_pass(ctx, "flag-parity") if f.code == "F010"]
    # .metered is warmed; .metered.lineage is not — exactly the lattice
    assert len(found) == 1 and ".metered.lineage" in found[0].message


def test_flag_parity_variable_valued_flag_is_optional(tmp_path):
    """``kw["health"] = health_flag`` (runtime value) must generate BOTH
    the with- and without-health combos, exactly like ``health=flag``
    passed directly."""
    files = _surface_files()
    files["srnn_tpu/setups/mega.py"] = """
        def loop(cfg, state, health_flag):
            kw = {"metrics": True}
            kw["health"] = health_flag
            return evolve_donated(cfg, state, **kw)
        """
    ctx = make_repo(tmp_path, files)
    # .metered and .metered.health are both warmed: no findings — but
    # only if the no-health combo was actually generated and checked
    found = [f for f in run_pass(ctx, "flag-parity") if f.code == "F010"]
    assert found == []
    files["srnn_tpu/setups/mega.py"] = """
        def loop(cfg, state, lineage_flag):
            kw = {"metrics": True}
            kw["lineage"] = lineage_flag
            return evolve_donated(cfg, state, **kw)
        """
    ctx = make_repo(tmp_path / "b", files)
    found = [f for f in run_pass(ctx, "flag-parity") if f.code == "F010"]
    assert len(found) == 1 and ".metered.lineage" in found[0].message


def test_flag_parity_unresolvable_dispatch_warns(tmp_path):
    files = _surface_files()
    files["srnn_tpu/setups/mega.py"] = """
        def loop(cfg, state, kwargs):
            return evolve_donated(cfg, state, **kwargs)
        """
    ctx = make_repo(tmp_path, files)
    found = [f for f in run_pass(ctx, "flag-parity") if f.code == "F012"]
    assert len(found) == 1 and found[0].severity == WARNING


def test_flag_parity_stale_registry_is_loud(tmp_path):
    """A vanished entries generator reports F011 — and a live setups
    dispatch of that surface must not crash the rest of the scan (the
    exact shape ROADMAP item 1's refactor will produce mid-rename)."""
    files = _surface_files()
    files["srnn_tpu/utils/aot.py"] = _AOT_FIXTURE.replace(
        "def _soup_entries", "def _renamed_soup_entries")
    files["srnn_tpu/setups/mega.py"] = """
        def loop(cfg, state):
            out = evolve_donated(cfg, state, metrics=True)
            state = out[0]
            return state
        """
    ctx = make_repo(tmp_path, files)
    found = run_pass(ctx, "flag-parity")
    assert "F011" in codes(found)
    assert not [f for f in found if f.code == "F010"]


# ---------------------------------------------------------------------------
# jit purity
# ---------------------------------------------------------------------------


def test_jit_purity_time_in_scanned_body(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        import time
        import jax

        def step(carry, _):
            t = time.time()
            return carry + t, None

        def run(state):
            return jax.lax.scan(step, state, None, length=10)
        """})
    found = run_pass(ctx, "jit-purity")
    assert codes(found) == ["J002"]


def test_jit_purity_decorated_and_wrapped(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        import functools
        import numpy as np
        import jax

        @jax.jit
        def f(x):
            print("tracing", x)
            return x

        @functools.partial(jax.jit, static_argnames=("n",))
        def g(x, n):
            with open("/tmp/x") as fh:
                fh.read()
            return x

        def _h(x):
            global COUNT
            COUNT += 1
            return x + np.random.rand()

        h = jax.jit(_h, donate_argnums=(0,))
        """})
    assert codes(run_pass(ctx, "jit-purity")) == \
        ["J001", "J003", "J004", "J005"]


def test_jit_purity_jax_random_spelling_is_clean(tmp_path):
    """``from jax import random`` inside traced code is the trace-safe
    spelling and must not be flagged; stdlib ``import random`` must."""
    ctx = make_repo(tmp_path, {
        "srnn_tpu/good.py": """
            import jax
            from jax import random

            @jax.jit
            def f(key):
                return random.normal(random.split(key)[0], (3,))
            """,
        "srnn_tpu/bad.py": """
            import random
            import jax

            @jax.jit
            def f(x):
                return x + random.random()
            """})
    found = run_pass(ctx, "jit-purity")
    assert codes(found) == ["J003"]
    assert found[0].path == "srnn_tpu/bad.py"
    # numpy's module-level random import is a host RNG too
    numpy_ctx = make_repo(tmp_path / "np", {"srnn_tpu/mod.py": """
        import jax
        from numpy import random

        @jax.jit
        def f(x):
            return x + random.rand()
        """})
    assert codes(run_pass(numpy_ctx, "jit-purity")) == ["J003"]


def test_jit_purity_kernel_and_clean_host_code(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        import time
        import numpy as np
        from jax.experimental import pallas as pl

        def kernel(w_ref, o_ref):
            o_ref[...] = w_ref[...] * np.random.rand()

        def call(w):
            return pl.pallas_call(kernel, out_shape=None)(w)

        def host_loop(run_dir):
            t0 = time.time()                 # host code: fine
            print("starting", run_dir)       # host code: not this pass
            return time.time() - t0
        """})
    assert codes(run_pass(ctx, "jit-purity")) == ["J003"]


# ---------------------------------------------------------------------------
# fault taxonomy
# ---------------------------------------------------------------------------


_SUPERVISOR_OK = """
    import re

    EXIT_RECOVERED = 3
    EXIT_RETRIES_EXHAUSTED = 69
    EXIT_PREEMPTED_CLEAN = 75

    _DETERMINISTIC_XLA_RE = re.compile(
        r"RESOURCE_EXHAUSTED|INVALID_ARGUMENT")

    class Preempted(Exception):
        pass

    def classify_fault(exc):
        from ..utils.pipeline import StallError, WriterError
        if isinstance(exc, Preempted):
            return "preempt"
        if isinstance(exc, StallError):
            return "stall"
        if isinstance(exc, WriterError):
            return "io"
        if _DETERMINISTIC_XLA_RE.search(str(exc)):
            return "fatal"
        return "fatal"
    """

_WATCH_OK = """\
case "$rc" in
    0) echo ok ;;
    3) echo recovered ;;
    75) echo preempted ;;
    69) echo exhausted ;;
    *) echo wedge ;;
esac
"""

_MAIN_OK = "# exit vocabulary: 0 clean, 3 recovered, 69 exhausted, 75 preempted\n"


def _taxonomy_files(supervisor=_SUPERVISOR_OK, watch=_WATCH_OK,
                    window=_WATCH_OK, main=_MAIN_OK):
    return {
        "srnn_tpu/resilience/supervisor.py": supervisor,
        "srnn_tpu/setups/__main__.py": main,
        "srnn_tpu/utils/pipeline.py": """
            class StallError(Exception):
                pass

            class WriterError(Exception):
                pass

            def f(job):
                raise WriterError("job died")

            def g():
                raise StallError("deadline")
            """,
        "scripts/tpu_watch.sh": watch,
        "scripts/tpu_window.sh": window,
    }


def test_fault_taxonomy_clean_fixture(tmp_path):
    ctx = make_repo(tmp_path, _taxonomy_files())
    assert run_pass(ctx, "fault-taxonomy") == []


def test_fault_taxonomy_unclassified_raise(tmp_path):
    sup = _SUPERVISOR_OK.replace(
        '        if isinstance(exc, WriterError):\n'
        '            return "io"\n', '')
    ctx = make_repo(tmp_path, _taxonomy_files(supervisor=sup))
    found = [f for f in run_pass(ctx, "fault-taxonomy")
             if f.code == "T001"]
    assert len(found) == 1
    assert "WriterError" in found[0].message
    assert found[0].path == "srnn_tpu/utils/pipeline.py"


def test_fault_taxonomy_bogus_status_and_dead_regex(tmp_path):
    sup = _SUPERVISOR_OK.replace("RESOURCE_EXHAUSTED", "RESOURCE_EXHASTED")
    sup += "\n    _DEAD_RE = re.compile(r'DATA_LOSS')\n"
    ctx = make_repo(tmp_path, _taxonomy_files(supervisor=sup))
    found = run_pass(ctx, "fault-taxonomy")
    assert "T002" in codes(found) and "T003" in codes(found)
    assert any("RESOURCE_EXHASTED" in f.message for f in found)


def test_fault_taxonomy_stale_exit_codes(tmp_path):
    watch = _WATCH_OK.replace("    75) echo preempted ;;\n", "")
    window = _WATCH_OK + "\nexit 3\n"
    main = "# exit vocabulary: 0 clean, 3 recovered, 69 exhausted\n"
    ctx = make_repo(tmp_path, _taxonomy_files(watch=watch, window=window,
                                              main=main))
    found = run_pass(ctx, "fault-taxonomy")
    got = codes(found)
    assert "T004" in got    # 75 not named in setups/__main__.py
    assert "T005" in got    # no case arm for 75 in tpu_watch.sh
    assert "T006" in got    # tpu_window.sh claims exit 3 for itself
    # comments never trip the collision check, and an earlier comment
    # must not skew the reported line of a real collision below it
    commented = make_repo(tmp_path / "c", _taxonomy_files(
        window=_WATCH_OK + "\n# a comment naming exit 75 is fine\n"))
    assert "T006" not in codes(run_pass(commented, "fault-taxonomy"))
    skewed = make_repo(tmp_path / "s", _taxonomy_files(
        window=_WATCH_OK + "\n# long comment before the bug\nexit 69\n"))
    hits = [f for f in run_pass(skewed, "fault-taxonomy")
            if f.code == "T006"]
    assert len(hits) == 1
    assert hits[0].line == len(_WATCH_OK.splitlines()) + 3


_SUPERVISOR_KINDS = _SUPERVISOR_OK + """
    DEVICE_LOSS = "device_loss"
    STALL = "stall"
    IO = "io"
    FATAL = "fatal"
    RETRYABLE = (DEVICE_LOSS, STALL, IO)
    """

_SERVICE_OK = """
    from ..resilience.supervisor import DEVICE_LOSS, IO, STALL

    DISPATCH_RETRYABLE = (DEVICE_LOSS, IO, STALL)
    """

_CHAOS_OK = """
    SERVE_FAULT_KINDS = ("device_loss", "io", "stall")
    """


def test_fault_taxonomy_serve_menus_clean(tmp_path):
    files = _taxonomy_files(supervisor=_SUPERVISOR_KINDS)
    files["srnn_tpu/serve/service.py"] = _SERVICE_OK
    files["srnn_tpu/resilience/chaos.py"] = _CHAOS_OK
    ctx = make_repo(tmp_path, files)
    assert run_pass(ctx, "fault-taxonomy") == []


def test_fault_taxonomy_serve_retry_menu_drift(tmp_path):
    # FATAL in the service's retry menu: retries a fault the taxonomy
    # calls fatal -> T008; a chaos menu kind outside the retryable
    # values -> T009; a service module with no menu at all -> T008 stale
    files = _taxonomy_files(supervisor=_SUPERVISOR_KINDS)
    files["srnn_tpu/serve/service.py"] = """
        from ..resilience.supervisor import FATAL, IO

        DISPATCH_RETRYABLE = (FATAL, IO)
        """
    files["srnn_tpu/resilience/chaos.py"] = """
        SERVE_FAULT_KINDS = ("io", "preempt")
        """
    found = run_pass(make_repo(tmp_path, files), "fault-taxonomy")
    got = codes(found)
    assert "T008" in got and "T009" in got
    assert any("FATAL" in f.message for f in found)
    assert any("preempt" in f.message for f in found)
    stale = _taxonomy_files(supervisor=_SUPERVISOR_KINDS)
    stale["srnn_tpu/serve/service.py"] = "X = 1\n"
    found = run_pass(make_repo(tmp_path / "stale", stale),
                     "fault-taxonomy")
    assert [f.code for f in found] == ["T008"]
    assert "DISPATCH_RETRYABLE" in found[0].message
    # a chaos module whose menu went unscannable reports, never skips
    nomenu = _taxonomy_files(supervisor=_SUPERVISOR_KINDS)
    nomenu["srnn_tpu/serve/service.py"] = _SERVICE_OK
    nomenu["srnn_tpu/resilience/chaos.py"] = "Y = 2\n"
    found = run_pass(make_repo(tmp_path / "nomenu", nomenu),
                     "fault-taxonomy")
    assert [f.code for f in found] == ["T009"]
    assert "unscannable" in found[0].message


# ---------------------------------------------------------------------------
# migrated hygiene passes still fire
# ---------------------------------------------------------------------------


def test_stray_prints_fires_and_allows_stderr(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        import sys

        def f():
            print("to stdout")
            print("diag", file=sys.stderr)
        """})
    found = run_pass(ctx, "stray-prints")
    assert codes(found) == ["P001"] and found[0].line == 5


def test_thread_hygiene_fires(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        import threading
        from .utils.pipeline import spawn_thread

        def f(target):
            t = threading.Thread(target=target)
            s = spawn_thread(target, daemon=True)
            return t, s
        """})
    assert codes(run_pass(ctx, "thread-hygiene")) == ["H001", "H002"]


def test_thread_hygiene_second_daemon_in_whitelisted_file(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/telemetry/flightrec.py": """
        from ..utils.pipeline import spawn_thread

        def a(x):
            return spawn_thread(x, daemon=True)

        def b(x):
            return spawn_thread(x, daemon=True)
        """})
    assert codes(run_pass(ctx, "thread-hygiene")) == ["H003"]


def test_metric_names_fires_on_unknown_and_miskinded(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        def f(registry):
            registry.counter("totally_bogus_metric_total").inc(1)
            registry.gauge("soup_generations_total").set(1)
        """})
    found = [f for f in run_pass(ctx, "metric-names")
             if f.code in ("M001", "M002")]
    assert codes(found) == ["M001", "M002"]


def test_metric_liveness_fires_on_declared_but_never_emitted(tmp_path):
    """M005: a name in CANONICAL_METRICS with no emission site anywhere
    in the package is dead dashboard weight.  The fixture repo emits ONE
    canonical name (as a literal registration) and spells a second in a
    runtime-table dict — every other canonical name must be reported
    dead, and those two must not."""
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        EVENT_COUNTERS = {"attacking": ("soup_attacks_total", "help")}

        def f(registry):
            registry.counter("soup_generations_total").inc(1)
        """})
    dead = {f.message.split("'")[1] for f in run_pass(ctx, "metric-names")
            if f.code == "M005"}
    assert "soup_generations_total" not in dead      # literal registration
    assert "soup_attacks_total" not in dead          # runtime-table spell
    assert "soup_hlo_flops" in dead                  # nothing emits it here
    assert "serve_tenant_flops_total" in dead


def test_metric_liveness_covers_archive_family(tmp_path):
    """The ``soup_archive_*`` family (the cross-run observatory's
    exposition) rides the same governance: every archive name is M005-
    dead in a fixture repo that never spells it, goes live once ONE
    module registers it, and a mis-kinded registration (the counter
    declared as a gauge) fires M002."""
    archive_names = ("soup_archive_runs", "soup_archive_runs_ingested_total",
                     "soup_archive_drift_ratio", "soup_archive_drift_legs")
    ctx = make_repo(tmp_path / "dead", {"srnn_tpu/mod.py": """
        def f(registry):
            registry.counter("soup_generations_total").inc(1)
        """})
    dead = {f.message.split("'")[1] for f in run_pass(ctx, "metric-names")
            if f.code == "M005"}
    assert set(archive_names) <= dead  # the gate SEES the new family

    ctx = make_repo(tmp_path / "live", {"srnn_tpu/mod.py": """
        def f(registry):
            registry.gauge("soup_archive_runs").set(3)
            registry.counter("soup_archive_runs_ingested_total").inc(1)
            registry.gauge("soup_archive_drift_ratio").set(0.9)
            registry.gauge("soup_archive_drift_legs").set(0)
        """})
    findings = run_pass(ctx, "metric-names")
    dead = {f.message.split("'")[1] for f in findings if f.code == "M005"}
    assert not dead & set(archive_names)
    assert not [f for f in findings if f.code == "M002"]

    ctx = make_repo(tmp_path / "miskind", {"srnn_tpu/mod.py": """
        def f(registry):
            registry.gauge("soup_archive_runs_ingested_total").set(1)
        """})
    bad = [f for f in run_pass(ctx, "metric-names") if f.code == "M002"]
    assert len(bad) == 1
    assert "soup_archive_runs_ingested_total" in bad[0].message


def test_metric_references_cover_archive_names(tmp_path):
    """M006 over the new family: a rule watching a typo'd archive name
    fires; one watching the canonical spelling does not."""
    ctx = make_repo(tmp_path, {"srnn_tpu/rules.py": """
        def my_rules(Rule):
            return [Rule(name="ok", metric="soup_archive_drift_legs",
                         kind="threshold", value=1.0),
                    Rule(name="bad", metric="soup_archive_drift_leg",
                         kind="threshold", value=1.0)]
        """})
    refs = [f.message.split("'")[1]
            for f in run_pass(ctx, "metric-names") if f.code == "M006"]
    assert refs == ["soup_archive_drift_leg"]


def test_metric_liveness_clean_on_real_repo(repo_ctx):
    """The real package has an emission site for every declared name
    (this is the gate that keeps names.py from accumulating dead
    metrics as new families land)."""
    assert [f for f in run_pass(repo_ctx, "metric-names")
            if f.code == "M005"] == []


def test_metric_references_fire_on_unknown_rule_and_allowlist(tmp_path):
    """M006 (the inverse of M005): a ``Rule(metric=...)`` alert rule or
    a ``HEALTHZ_METRICS`` allowlist entry naming a metric outside
    CANONICAL_METRICS is a silently-dead watch — the fixture seeds one
    bad rule, one good rule, and one bad allowlist entry."""
    ctx = make_repo(tmp_path, {
        "srnn_tpu/telemetry/exporter.py": """
        HEALTHZ_METRICS = ("heartbeat_generation", "no_such_gauge")
        """,
        "srnn_tpu/rules.py": """
        def my_rules(Rule):
            return [Rule(name="ok", metric="soup_health_nan_frac",
                         kind="threshold", value=0.5),
                    Rule(name="bad", metric="not_declared_anywhere",
                         kind="threshold", value=1.0)]
        """})
    found = [f for f in run_pass(ctx, "metric-names") if f.code == "M006"]
    refs = sorted(f.message.split("'")[1] for f in found)
    assert refs == ["no_such_gauge", "not_declared_anywhere"]
    paths = {f.path for f in found}
    assert any(p.endswith("exporter.py") for p in paths)
    assert any(p.endswith("rules.py") for p in paths)


def test_metric_references_clean_on_real_repo(repo_ctx):
    """Every metric the shipped alert rule tables and the /healthz
    allowlist reference is declared (keeps a rule from silently
    watching a name nobody can emit)."""
    assert [f for f in run_pass(repo_ctx, "metric-names")
            if f.code == "M006"] == []


def test_span_names_fire_on_undeclared_emissions(tmp_path):
    """S001: each of the three emission idioms (SpanStream emit/timed,
    the serve ``span=`` keyword rows, the pool front's ``_span_row``)
    fires on a name outside CANONICAL_SPANS; an undotted ``.emit()``
    call (some unrelated API) is NOT a span emission."""
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        def f(stream, ticket, queue):
            stream.emit("bogus.span", 1.0, 0.5)
            with stream.timed("front.bogus"):
                pass
            _span_row(ticket, "serve.bogus", 7, start_s=0.0, seconds=0.1)
            _event_row(kind="span", span="serve.ticket.bogus", span_id=1)
            queue.emit("message")            # undotted: not a span idiom
            stream.emit("serve.ticket", 1.0, 0.5)   # declared: clean
        """})
    found = [f for f in run_pass(ctx, "span-names") if f.code == "S001"]
    bad = sorted(f.message.split("'")[1] for f in found)
    assert bad == ["bogus.span", "front.bogus", "serve.bogus",
                   "serve.ticket.bogus"]
    assert all(f.path == "srnn_tpu/mod.py" for f in found)


def test_span_liveness_fires_on_declared_but_never_emitted(tmp_path):
    """S002 (the M005 twin): the fixture emits one canonical name as a
    literal, spells a second as a bare string constant (the
    ``relay_name = ... if ... else ...`` idiom), and covers the chunk
    families through an f-string SUFFIX — every other declared span is
    dead, and those must not be."""
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        def f(stream, stage, replays):
            stream.emit("serve.ticket", 1.0, 0.5)
            stream.emit(f"{stage}.chunk", 1.0, 0.5)
            name = "front.replay" if replays else "front.relay"
            return name
        """})
    dead = {f.message.split("'")[1] for f in run_pass(ctx, "span-names")
            if f.code == "S002"}
    assert "serve.ticket" not in dead         # literal emission
    assert "front.relay" not in dead          # whole-constant evidence
    assert "front.replay" not in dead
    assert "mega_soup.chunk" not in dead      # f-string suffix evidence
    assert "mega_multisoup.chunk" not in dead
    assert "front.assign" in dead             # nothing spells it here
    assert "serve.admit" in dead


def test_span_names_scan_going_dark_is_loud(tmp_path):
    """S003: a fixture with no span emissions at all means the pass's
    idiom recognition broke (or the idioms moved) — one loud finding,
    not a silently-green gate."""
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": "X = 1\n"})
    assert codes(run_pass(ctx, "span-names")) == ["S003"]


def test_span_liveness_clean_on_real_repo(repo_ctx):
    """Every declared span has an emission site in the real package —
    the gate that keeps CANONICAL_SPANS from accumulating dead lanes."""
    assert [f for f in run_pass(repo_ctx, "span-names")
            if f.code == "S002"] == []


# ---------------------------------------------------------------------------
# waivers / baseline machinery
# ---------------------------------------------------------------------------


def test_waiver_suppresses_and_stale_waiver_reported(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        def f():
            print("oops")
        """})
    waivers = tmp_path / "waivers.txt"
    waivers.write_text(
        "stray-prints srnn_tpu/mod.py P001 demo print, removed next PR\n"
        "thread-hygiene srnn_tpu/gone.py H001 covered a deleted file\n")
    result = run_analysis(ctx, select(["stray-prints", "thread-hygiene"]),
                          waiver_file=str(waivers))
    assert not result.errors
    assert len(result.waived) == 1
    stale = [f for f in result.findings if f.code == "W002"]
    assert len(stale) == 1 and stale[0].severity == WARNING
    # a single-pass run must NOT judge the other pass's waiver stale
    solo = run_analysis(ctx, select(["stray-prints"]),
                        waiver_file=str(waivers))
    assert not [f for f in solo.findings if f.code == "W002"]


def test_waiver_match_substring_narrows(tmp_path):
    """A match="..." waiver covers only findings whose message contains
    the substring — a second distinct finding of the same code in the
    same file still surfaces (the baseline cannot grow a blanket hole)."""
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": """
        import sys

        def f():
            print("one")

        def g(x):
            print("two", x)
        """})
    waivers = tmp_path / "waivers.txt"
    waivers.write_text('stray-prints srnn_tpu/mod.py P001 '
                       'match="no such text" demo narrow waiver\n')
    result = run_analysis(ctx, select(["stray-prints"]),
                          waiver_file=str(waivers))
    # the substring matches neither finding: both surface, waiver stale
    assert len([f for f in result.errors if f.code == "P001"]) == 2
    assert [f.code for f in result.findings if f.pass_id == "waivers"] \
        == ["W002"]


def test_reasonless_waiver_is_a_finding(tmp_path):
    ctx = make_repo(tmp_path, {"srnn_tpu/mod.py": "X = 1\n"})
    waivers = tmp_path / "waivers.txt"
    waivers.write_text("stray-prints srnn_tpu/mod.py P001\n")
    loaded, problems = load_waivers(str(waivers))
    assert not loaded
    assert len(problems) == 1 and problems[0].code == "W001"
    result = run_analysis(ctx, select(["stray-prints"]),
                          waiver_file=str(waivers))
    assert result.exit_code == 1


def test_unparseable_file_is_a_finding_not_a_blind_spot(tmp_path):
    """A file the compiler rejects must surface as core/E001 — the old
    walkers crashed loudly on it; silently analyzing an empty AST would
    disable every gate for that file."""
    ctx = make_repo(tmp_path, {"srnn_tpu/broken.py": """
        def f(:
            print("never parsed")
        """})
    assert ctx.parse_errors and ctx.parse_errors[0].code == "E001"
    result = run_analysis(ctx, select(["stray-prints"]),
                          waiver_file=str(tmp_path / "none.txt"))
    assert result.exit_code == 1
    assert [f.code for f in result.errors] == ["E001"]


def test_cli_internal_error_exits_three(tmp_path, capsys, monkeypatch):
    """An analyzer crash must exit 3, never the findings code 1 — the
    bench preflight records 3 as inconclusive instead of blocking."""
    from srnn_tpu.analysis import __main__ as cli

    def boom(*a, **k):
        raise RuntimeError("analyzer bug")

    monkeypatch.setattr(cli, "run_analysis", boom)
    assert cli.main([]) == 3
    capsys.readouterr()


def test_analyzer_is_fast(repo_ctx):
    """The acceptance bound: the full analyzer (context already built)
    must stay far under the 30s CI budget — warn well before the cliff."""
    import time

    t0 = time.monotonic()
    run_analysis(repo_ctx, select(None))
    assert time.monotonic() - t0 < 15.0
