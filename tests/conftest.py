"""Test env: force an 8-device virtual CPU platform so sharding/mesh logic is
exercised without TPU hardware (SURVEY §4 implication (c)).

pytest's plugin machinery imports jax before this file runs, so the
JAX_PLATFORMS env var is already snapshotted — we must go through
jax.config.update instead.  XLA_FLAGS is still read at backend-init time,
which hasn't happened yet, so the env route works for the device count."""

import os
import shutil
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")

# A test run hard-killed mid-compile can leave a truncated entry in the
# shared compilation cache, and XLA SEGFAULTS deserializing it on every
# later run (observed: repeatable crash in backend_compile_and_load until
# the cache was wiped).  Crash detection: a PER-SESSION marker file
# (.session_running.<pid>) exists for the duration of each session, so
# concurrent sessions never clobber each other's markers; finding a marker
# whose owner pid is dead at startup means that run died uncleanly — wipe
# the cache, unless another session is LIVE right now (its in-flight
# compiles would be yanked out from under it; the poison, if any, will be
# caught by whichever session starts after everything quiesces).
_CACHE_DIR = os.environ["JAX_COMPILATION_CACHE_DIR"]
_CRASH_MARKER = os.path.join(
    _CACHE_DIR, f".session_running.{os.getpid()}") if _CACHE_DIR else None
if _CRASH_MARKER:
    import glob as _glob

    _stale, _live = [], []
    for _m in _glob.glob(os.path.join(_CACHE_DIR, ".session_running.*")):
        try:
            _owner = int(_m.rsplit(".", 1)[1])
        except ValueError:
            _owner = 0
        (_live if _owner and os.path.exists(f"/proc/{_owner}")
         else _stale).append(_m)
    # legacy single-marker name from earlier rounds (pid recorded INSIDE
    # the file): still counts — a crash under the old conftest must not
    # leave its poison undetected after the upgrade
    _legacy = os.path.join(_CACHE_DIR, ".session_running")
    if os.path.exists(_legacy):
        try:
            _owner = int(open(_legacy).read().strip() or "0")
        except (OSError, ValueError):
            _owner = 0
        (_live if _owner and os.path.exists(f"/proc/{_owner}")
         else _stale).append(_legacy)
    if _stale and not _live:
        shutil.rmtree(_CACHE_DIR, ignore_errors=True)
    else:
        for _m in _stale:  # dead markers under a live session: just tidy
            try:
                os.remove(_m)
            except OSError:
                pass
    os.makedirs(_CACHE_DIR, exist_ok=True)
    with open(_CRASH_MARKER, "w") as _f:
        _f.write(str(os.getpid()))


def pytest_sessionfinish(session, exitstatus):
    if _CRASH_MARKER:
        try:
            os.remove(_CRASH_MARKER)
        except OSError:
            pass

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest


@pytest.fixture(scope="session")
def mesh():
    """The 8-device virtual soup mesh (shared by the sharded-soup and
    capture test modules)."""
    from srnn_tpu.parallel import soup_mesh

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return soup_mesh()
