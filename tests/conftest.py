"""Test env: force an 8-device virtual CPU platform so sharding/mesh logic is
exercised without TPU hardware (SURVEY §4 implication (c)).

pytest's plugin machinery imports jax before this file runs, so the
JAX_PLATFORMS env var is already snapshotted — we must go through
jax.config.update instead.  XLA_FLAGS is still read at backend-init time,
which hasn't happened yet, so the env route works for the device count."""

import os
import shutil
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")

# A test run hard-killed mid-compile can leave a truncated entry in the
# shared compilation cache, and XLA SEGFAULTS deserializing it on every
# later run (observed: repeatable crash in backend_compile_and_load until
# the cache was wiped).  Crash detection: a marker file exists for the
# duration of a session; finding one at startup means the previous run
# died uncleanly — wipe the cache rather than risk reading poison.
_CACHE_DIR = os.environ["JAX_COMPILATION_CACHE_DIR"]
_CRASH_MARKER = os.path.join(_CACHE_DIR, ".session_running") if _CACHE_DIR else None
if _CRASH_MARKER:
    if os.path.exists(_CRASH_MARKER):
        # the marker records the owning pid: a LIVE owner is a concurrent
        # session (leave its cache alone); a dead one crashed mid-write and
        # its cache may hold truncated poison — wipe
        try:
            owner = int(open(_CRASH_MARKER).read().strip() or "0")
        except (OSError, ValueError):
            owner = 0
        if not (owner and os.path.exists(f"/proc/{owner}")):
            shutil.rmtree(_CACHE_DIR, ignore_errors=True)
    os.makedirs(_CACHE_DIR, exist_ok=True)
    with open(_CRASH_MARKER, "w") as _f:
        _f.write(str(os.getpid()))


def pytest_sessionfinish(session, exitstatus):
    if _CRASH_MARKER:
        try:
            os.remove(_CRASH_MARKER)
        except OSError:
            pass

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest


@pytest.fixture(scope="session")
def mesh():
    """The 8-device virtual soup mesh (shared by the sharded-soup and
    capture test modules)."""
    from srnn_tpu.parallel import soup_mesh

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return soup_mesh()
