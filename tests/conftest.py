"""Test env: force an 8-device virtual CPU platform so sharding/mesh logic is
exercised without TPU hardware (SURVEY §4 implication (c)).  Must run before
jax initializes its backends, hence top of conftest."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env presets a TPU platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
