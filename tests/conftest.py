"""Test env: force an 8-device virtual CPU platform so sharding/mesh logic is
exercised without TPU hardware (SURVEY §4 implication (c)).

pytest's plugin machinery imports jax before this file runs, so the
JAX_PLATFORMS env var is already snapshotted — we must go through
jax.config.update instead.  XLA_FLAGS is still read at backend-init time,
which hasn't happened yet, so the env route works for the device count."""

import os
import shutil
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")

# A test run hard-killed mid-compile can leave a truncated entry in the
# shared compilation cache, and XLA SEGFAULTS deserializing it on every
# later run (observed: repeatable crash in backend_compile_and_load until
# the poisoned entry was gone).  Crash detection: a PER-SESSION marker file
# (.session_running.<pid>) exists for the duration of each session, so
# concurrent sessions never clobber each other's markers; finding a marker
# whose owner pid is dead at startup means that run died uncleanly.
#
# Recovery is SELECTIVE, not a wholesale wipe: a torn entry can only be
# one the dead session was writing AT the moment it died, so only the
# TAIL of its writes is suspect — files modified within a short window
# before the dead session's newest cache write (its last act before the
# kill), plus zero-length files (a torn write at any age).  Everything
# else it wrote completed normally and stays; this is what keeps tier-1
# warm inside its 870s budget.  (Two earlier policies both failed: a full
# wipe cost ~200s of recompiles after EVERY killed session, and purging
# everything-since-session-start re-cooled exactly the entries a
# timed-out run had just compiled, so a suite that timed out once could
# never re-warm — each retry purged the previous retry's work.)  If
# another session is LIVE right now, nothing is removed (its in-flight
# compiles would be yanked out from under it; the poison, if any, is
# caught by whichever session starts after everything quiesces).
_CACHE_DIR = os.environ["JAX_COMPILATION_CACHE_DIR"]
_CRASH_MARKER = os.path.join(
    _CACHE_DIR, f".session_running.{os.getpid()}") if _CACHE_DIR else None
_PURGE_TAIL_S = 60.0


def _purge_suspect_cache_entries(cache_dir, since_mtime, tail_only=True):
    """Remove the cache entries a crashed session may have left torn:
    zero-length files, and — with ``tail_only`` (the single-crash case) —
    files modified within ``_PURGE_TAIL_S`` of the newest
    post-``since_mtime`` write (the dead session's final moments; a kill
    tears at most the write in flight, not the whole run's output).  With
    ``tail_only=False`` (several dead sessions at once: their death times
    are indistinguishable, so a single global tail could miss the
    earlier-killed session's torn entry) everything since ``since_mtime``
    goes.  Marker files manage themselves."""
    try:
        with os.scandir(cache_dir) as entries:
            stats = [(e.path, e.stat()) for e in entries
                     if e.is_file()
                     and not e.name.startswith(".session_running")]
    except OSError:
        return 0
    newest = max((st.st_mtime for _p, st in stats
                  if st.st_mtime >= since_mtime), default=None)
    removed = 0
    for path, st in stats:
        suspect = newest is not None and st.st_mtime >= (
            max(since_mtime, newest - _PURGE_TAIL_S) if tail_only
            else since_mtime)
        if st.st_size == 0 or suspect:
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
    return removed


if _CRASH_MARKER:
    import glob as _glob

    _stale, _live = [], []
    for _m in _glob.glob(os.path.join(_CACHE_DIR, ".session_running.*")):
        try:
            _owner = int(_m.rsplit(".", 1)[1])
        except ValueError:
            _owner = 0
        (_live if _owner and os.path.exists(f"/proc/{_owner}")
         else _stale).append(_m)
    # legacy single-marker name from earlier rounds (pid recorded INSIDE
    # the file): still counts — a crash under the old conftest must not
    # leave its poison undetected after the upgrade
    _legacy = os.path.join(_CACHE_DIR, ".session_running")
    if os.path.exists(_legacy):
        try:
            _owner = int(open(_legacy).read().strip() or "0")
        except (OSError, ValueError):
            _owner = 0
        (_live if _owner and os.path.exists(f"/proc/{_owner}")
         else _stale).append(_legacy)
    if _stale and not _live:
        # earliest dead-session start bounds every suspect write; with
        # SEVERAL dead sessions their death times can't be told apart, so
        # the warm-friendly tail heuristic degrades to the full
        # since-marker purge for that (rare) case
        _since = min((os.path.getmtime(_m) for _m in _stale
                      if os.path.exists(_m)), default=0.0)
        _purge_suspect_cache_entries(_CACHE_DIR, _since,
                                     tail_only=len(_stale) == 1)
        for _m in _stale:  # tidy ONLY after the purge actually ran —
            try:           # removing a dead marker while another session
                os.remove(_m)  # is live would forget its poison forever
            except OSError:
                pass
    os.makedirs(_CACHE_DIR, exist_ok=True)
    with open(_CRASH_MARKER, "w") as _f:
        _f.write(str(os.getpid()))


def pytest_sessionfinish(session, exitstatus):
    if _CRASH_MARKER:
        try:
            os.remove(_CRASH_MARKER)
        except OSError:
            pass

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; register the marker so strict runs and
    # --markers stay clean
    config.addinivalue_line(
        "markers",
        "slow: heavyweight e2e tests excluded from the tier-1 budget "
        "(run explicitly or with -m slow)")


@pytest.fixture(scope="session")
def mesh():
    """The 8-device virtual soup mesh (shared by the sharded-soup and
    capture test modules)."""
    from srnn_tpu.parallel import soup_mesh

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return soup_mesh()
