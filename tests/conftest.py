"""Test env: force an 8-device virtual CPU platform so sharding/mesh logic is
exercised without TPU hardware (SURVEY §4 implication (c)).

pytest's plugin machinery imports jax before this file runs, so the
JAX_PLATFORMS env var is already snapshotted — we must go through
jax.config.update instead.  XLA_FLAGS is still read at backend-init time,
which hasn't happened yet, so the env route works for the device count."""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest


@pytest.fixture(scope="session")
def mesh():
    """The 8-device virtual soup mesh (shared by the sharded-soup and
    capture test modules)."""
    from srnn_tpu.parallel import soup_mesh

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return soup_mesh()
