"""Soup population dynamics (reference soup.py:10-108)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology
from srnn_tpu.ops.predicates import CLS_DIVERGENT, CLS_FIX_OTHER, CLS_FIX_ZERO, CLS_OTHER
from srnn_tpu.soup import (
    ACT_ATTACK,
    ACT_DIV_DEAD,
    ACT_LEARN,
    ACT_NONE,
    ACT_TRAIN,
    ACT_ZERO_DEAD,
    SoupConfig,
    SoupState,
    count,
    evolve,
    evolve_step,
    seed,
)
from tests.test_apply import WW


def mkconfig(**kw):
    base = dict(topo=WW, size=10)
    base.update(kw)
    return SoupConfig(**base)


def test_seed_population():
    cfg = mkconfig(size=7)
    s = seed(cfg, jax.random.key(0))
    assert s.weights.shape == (7, 14)
    assert s.uids.tolist() == list(range(7))
    assert int(s.next_uid) == 7
    assert int(s.time) == 0


@pytest.mark.parametrize("mode", ["parallel", "sequential"])
def test_evolve_advances_time_and_stays_finite_shape(mode):
    cfg = mkconfig(mode=mode, attacking_rate=0.3, learn_from_rate=0.0, train=0)
    s = seed(cfg, jax.random.key(1))
    s2, ev = evolve_step(cfg, s)
    assert int(s2.time) == 1
    assert s2.weights.shape == s.weights.shape
    assert ev.action.shape == (10,)


def test_attack_changes_victims_only():
    cfg = mkconfig(attacking_rate=1.0, learn_from_rate=0.0, train=0, size=6)
    s = seed(cfg, jax.random.key(2))
    s2, ev = evolve_step(cfg, s)
    # with rate 1.0 everyone attacks someone; attackers all log 'attacking'
    assert ev.action.tolist() == [ACT_ATTACK] * 6
    assert np.all(np.asarray(ev.counterpart) >= 0)


def test_no_action_soup_is_static():
    cfg = mkconfig(attacking_rate=0.0, learn_from_rate=0.0, train=0)
    s = seed(cfg, jax.random.key(3))
    s2, ev = evolve_step(cfg, s)
    np.testing.assert_array_equal(np.asarray(s2.weights), np.asarray(s.weights))
    assert ev.action.tolist() == [ACT_NONE] * 10
    assert ev.counterpart.tolist() == [-1] * 10


def test_negative_rates_disable_phases():
    # sentinel -1 disables a phase (mixed-soup.py:83)
    cfg = mkconfig(attacking_rate=-1, learn_from_rate=-1, train=0)
    s = seed(cfg, jax.random.key(4))
    s2, ev = evolve_step(cfg, s)
    np.testing.assert_array_equal(np.asarray(s2.weights), np.asarray(s.weights))


def test_respawn_divergent_and_zero():
    cfg = mkconfig(size=4, attacking_rate=0.0, learn_from_rate=0.0, train=0,
                   remove_divergent=True, remove_zero=True)
    s = seed(cfg, jax.random.key(5))
    w = s.weights.at[0].set(jnp.nan).at[1].set(0.0)
    s = SoupState(w, s.uids, s.next_uid, s.time, s.key)
    s2, ev = evolve_step(cfg, s)
    assert ev.action.tolist()[:2] == [ACT_DIV_DEAD, ACT_ZERO_DEAD]
    # respawned rows are finite, non-zero, with fresh uids
    assert np.all(np.isfinite(np.asarray(s2.weights[0])))
    assert float(jnp.abs(s2.weights[1]).max()) > 1e-4
    assert s2.uids.tolist()[:2] == [4, 5]
    assert int(s2.next_uid) == 6
    assert ev.counterpart.tolist()[:2] == [4, 5]
    # survivors keep uid and weights
    assert s2.uids.tolist()[2:] == [2, 3]
    np.testing.assert_array_equal(np.asarray(s2.weights[2:]), np.asarray(s.weights[2:]))


def test_respawn_disabled_keeps_dead():
    cfg = mkconfig(size=3, attacking_rate=0.0, learn_from_rate=0.0)
    s = seed(cfg, jax.random.key(6))
    w = s.weights.at[0].set(jnp.nan)
    s = SoupState(w, s.uids, s.next_uid, s.time, s.key)
    s2, _ = evolve_step(cfg, s)
    assert bool(jnp.isnan(s2.weights[0]).any())
    assert int(s2.next_uid) == 3


def test_train_phase_trains_everyone():
    cfg = mkconfig(size=5, attacking_rate=0.0, learn_from_rate=0.0, train=3)
    s = seed(cfg, jax.random.key(7))
    s2, ev = evolve_step(cfg, s)
    assert ev.action.tolist() == [ACT_TRAIN] * 5
    assert not np.allclose(np.asarray(s2.weights), np.asarray(s.weights))
    assert np.all(np.isfinite(np.asarray(ev.loss)))


def test_learn_from_moves_learner():
    cfg = mkconfig(size=4, attacking_rate=0.0, learn_from_rate=1.0,
                   learn_from_severity=2, train=0)
    s = seed(cfg, jax.random.key(8))
    s2, ev = evolve_step(cfg, s)
    assert ev.action.tolist() == [ACT_LEARN] * 4
    assert not np.allclose(np.asarray(s2.weights), np.asarray(s.weights))


def test_soup_trajectory_run_reaches_nontrivial_fixpoints():
    """The BASELINE soup_trajectorys.py result: Soup(20, train=30,
    no attack/learn, 100 gen) -> majority fix_other, zero divergent/zero.
    Scaled down (train=30, 25 gen, N=8) for CI speed; self-training alone
    should already produce some non-trivial fixpoints and no deaths."""
    cfg = mkconfig(size=8, attacking_rate=-1, learn_from_rate=-1, train=30,
                   remove_divergent=True, remove_zero=True)
    s = seed(cfg, jax.random.key(9))
    final = evolve(cfg, s, generations=25)
    counts = count(cfg, final)
    assert int(counts[CLS_DIVERGENT]) == 0
    assert int(counts[CLS_FIX_ZERO]) == 0
    assert int(counts[CLS_FIX_OTHER]) > 0


def test_evolve_record_shapes():
    cfg = mkconfig(size=6, attacking_rate=0.5)
    s = seed(cfg, jax.random.key(10))
    final, (events, weights, uids) = evolve(cfg, s, generations=5, record=True)
    assert weights.shape == (5, 6, 14)
    assert uids.shape == (5, 6)
    assert events.action.shape == (5, 6)
    assert int(final.time) == 5


def test_sequential_mode_in_generation_attack_chain():
    """Sequential parity: an earlier particle's attack this generation is
    visible to later particles (reference in-order mutation)."""
    cfg = mkconfig(size=12, mode="sequential", attacking_rate=1.0,
                   learn_from_rate=0.0, train=0)
    s = seed(cfg, jax.random.key(11))
    s2, ev = evolve_step(cfg, s)
    assert ev.action.tolist() == [ACT_ATTACK] * 12
    assert s2.weights.shape == (12, 14)


@pytest.mark.parametrize("mode", ["parallel", "sequential"])
def test_modes_distributionally_similar(mode):
    """Both modes must drive an attack-only WW soup the same way
    statistically: without respawn, repeated attack converges the
    population to zero/divergence (BASELINE applying-fixpoint behavior)."""
    cfg = mkconfig(size=16, mode=mode, attacking_rate=0.5, learn_from_rate=0.0,
                   train=0)
    s = seed(cfg, jax.random.key(12))
    final = evolve(cfg, s, generations=60)
    counts = count(cfg, final)
    # most particles should have left 'other' by now
    assert int(counts[CLS_OTHER]) < 8


# ------------------------------------------------- population-major layout


@pytest.mark.parametrize("dyn", [
    dict(attacking_rate=0.5, learn_from_rate=-1.0, train=0),
    dict(attacking_rate=0.5, learn_from_rate=0.5, learn_from_severity=2, train=0),
    dict(attacking_rate=0.3, learn_from_rate=0.3, train=3,
         remove_divergent=True, remove_zero=True),
    dict(attacking_rate=0.3, learn_from_rate=0.3, train=3,
         train_mode="full_batch"),
])
def test_popmajor_matches_rowmajor(dyn):
    """layout='popmajor' draws the same PRNG stream as the row-major path, so
    gates/targets/respawns coincide and weights agree up to reassociation."""
    cfg_row = mkconfig(size=24, **dyn)
    cfg_pop = mkconfig(size=24, layout="popmajor", **dyn)
    st = seed(cfg_row, jax.random.key(5))
    row_s, row_ev = evolve_step(cfg_row, st)
    pop_s, pop_ev = evolve_step(cfg_pop, st)
    np.testing.assert_array_equal(np.asarray(row_ev.action), np.asarray(pop_ev.action))
    np.testing.assert_array_equal(np.asarray(row_ev.counterpart),
                                  np.asarray(pop_ev.counterpart))
    np.testing.assert_array_equal(np.asarray(row_s.uids), np.asarray(pop_s.uids))
    np.testing.assert_allclose(np.asarray(row_s.weights), np.asarray(pop_s.weights),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(row_ev.loss), np.asarray(pop_ev.loss),
                               rtol=1e-3, atol=1e-6)


def test_popmajor_evolve_many_generations_matches():
    cfg_row = mkconfig(size=16, attacking_rate=0.2, train=2,
                       remove_divergent=True, remove_zero=True)
    cfg_pop = cfg_row._replace(layout="popmajor")
    st = seed(cfg_row, jax.random.key(7))
    row = evolve(cfg_row, st, generations=10)
    pop = evolve(cfg_pop, st, generations=10)
    assert int(pop.time) == 10
    np.testing.assert_array_equal(np.asarray(row.uids), np.asarray(pop.uids))
    np.testing.assert_allclose(np.asarray(row.weights), np.asarray(pop.weights),
                               rtol=1e-3, atol=1e-5)


def test_popmajor_record_and_count():
    cfg = mkconfig(size=12, attacking_rate=0.3, train=1, layout="popmajor",
                   remove_divergent=True, remove_zero=True)
    st = seed(cfg, jax.random.key(1))
    final, (ev, w_hist, uid_hist) = evolve(cfg, st, generations=5, record=True)
    assert w_hist.shape == (5, 12, WW.num_weights)
    assert uid_hist.shape == (5, 12)
    assert int(count(cfg, final).sum()) == 12


def test_attack_impl_compact_matches_full_multi_generation():
    """attack_impl='compact' computes the transform on compacted attacked
    lanes only.  Same PRNG stream -> same gates/targets/respawns (uids
    EXACT); weights agree up to FMA contraction on the attacked lanes
    (<=1 ulp per step, here bounded loosely across 6 generations of
    dynamics).  The config is sized so the capacity (mean + 8 sd, 128-lane
    rounded) is genuinely below N — i.e. the compact branch, not the
    cap>=n full fallback, is what runs."""
    from srnn_tpu.soup import _attack_capacity

    cfg_full = mkconfig(size=512, attacking_rate=0.05, train=1,
                        remove_divergent=True, remove_zero=True,
                        layout="popmajor", respawn_draws="fused")
    assert _attack_capacity(512, 0.05) < 512
    cfg_compact = cfg_full._replace(attack_impl="compact")
    st = seed(cfg_full, jax.random.key(11))
    full = evolve(cfg_full, st, generations=6)
    compact = evolve(cfg_compact, st, generations=6)
    np.testing.assert_array_equal(np.asarray(full.uids),
                                  np.asarray(compact.uids))
    f, c = np.asarray(full.weights), np.asarray(compact.weights)
    finite = np.isfinite(f).all(axis=1) & np.isfinite(c).all(axis=1)
    np.testing.assert_allclose(c[finite], f[finite], rtol=1e-5, atol=1e-7)


def test_attack_compact_overflow_falls_back_to_full():
    """A capacity smaller than the attacked-lane count must trigger the
    lax.cond fallback: EVERY lane must carry the full path's update (the
    compact branch could only have written ``cap`` of them), to ulp
    tolerance (branch compilation inside lax.cond may contract FMAs
    differently than the standalone expression)."""
    from srnn_tpu.soup import _attack_popmajor_compact
    from srnn_tpu.ops.popmajor import apply_popmajor

    n = 32
    wT = jax.random.normal(jax.random.key(0), (WW.num_weights, n))
    att_idx = jnp.arange(n) % 7          # every lane attacked
    has_attacker = jnp.ones(n, bool)
    want = jnp.where(has_attacker[None, :],
                     apply_popmajor(WW, wT[:, jnp.clip(att_idx, 0)], wT), wT)
    got = _attack_popmajor_compact(WW, wT, att_idx, has_attacker, cap=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-7)
    # and none kept its pre-attack value (which a dropped-overflow compact
    # write pattern would leave behind)
    assert not np.any(np.all(np.asarray(got) == np.asarray(wT), axis=0))


def test_attack_compact_partial_lanes():
    """Sparse attacks (the realistic regime): unattacked lanes are BITWISE
    untouched; attacked lanes match the full path to <=1-ulp (FMA
    contraction at the narrower block width)."""
    from srnn_tpu.soup import _attack_popmajor_compact
    from srnn_tpu.ops.popmajor import apply_popmajor

    n = 48
    wT = jax.random.normal(jax.random.key(2), (WW.num_weights, n))
    has_attacker = (jnp.arange(n) % 11) == 0
    att_idx = jnp.where(has_attacker, (jnp.arange(n) * 5) % n, -1)
    want = jnp.where(has_attacker[None, :],
                     apply_popmajor(WW, wT[:, jnp.clip(att_idx, 0)], wT), wT)
    got = _attack_popmajor_compact(WW, wT, att_idx, has_attacker, cap=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-7)
    unchanged = ~np.asarray(has_attacker)
    np.testing.assert_array_equal(np.asarray(got)[:, unchanged],
                                  np.asarray(wT)[:, unchanged])


def test_attack_compact_rejects_rowmajor():
    with pytest.raises(ValueError, match="attack_impl"):
        evolve_step(mkconfig(attack_impl="compact"),
                    seed(mkconfig(), jax.random.key(0)))


def test_learn_from_impl_compact_matches_full():
    """learn_from_impl='compact' runs the imitation-SGD chain on the
    learner lanes only; same uid/gate exactness and FMA-level weight
    agreement as the attack compaction, across generations that mix all
    phases.  Sized so the capacity is genuinely below N."""
    from srnn_tpu.soup import _attack_capacity

    cfg_full = mkconfig(size=512, attacking_rate=0.05, learn_from_rate=0.05,
                        learn_from_severity=2, train=1,
                        remove_divergent=True, remove_zero=True,
                        layout="popmajor", respawn_draws="fused")
    assert _attack_capacity(512, 0.05) < 512
    cfg_c = cfg_full._replace(learn_from_impl="compact",
                              attack_impl="compact")
    st = seed(cfg_full, jax.random.key(21))
    full = evolve(cfg_full, st, generations=5)
    comp = evolve(cfg_c, st, generations=5)
    np.testing.assert_array_equal(np.asarray(full.uids),
                                  np.asarray(comp.uids))
    f, c = np.asarray(full.weights), np.asarray(comp.weights)
    finite = np.isfinite(f).all(axis=1) & np.isfinite(c).all(axis=1)
    np.testing.assert_allclose(c[finite], f[finite], rtol=5e-3, atol=1e-6)


def test_learn_compact_rejects_rowmajor():
    with pytest.raises(ValueError, match="learn_from_impl"):
        evolve_step(mkconfig(learn_from_impl="compact", learn_from_rate=0.5),
                    seed(mkconfig(), jax.random.key(0)))


def test_popmajor_rejects_unsupported_configs():
    with pytest.raises(ValueError):
        evolve_step(mkconfig(layout="popmajor", mode="sequential"),
                    seed(mkconfig(), jax.random.key(0)))
    # per-particle random shuffling is a per-lane gather — rowmajor-only
    shuf_topo = Topology("aggregating", width=2, depth=2, shuffler="random")
    shuf_cfg = SoupConfig(topo=shuf_topo, size=4, layout="popmajor")
    with pytest.raises(ValueError):
        evolve_step(shuf_cfg, seed(SoupConfig(topo=shuf_topo, size=4),
                                   jax.random.key(0)))


@pytest.mark.parametrize("topo", [
    Topology("aggregating", width=2, depth=2),
    Topology("aggregating", width=2, depth=2, aggregator="max"),
    Topology("aggregating", width=2, depth=2, aggregator="max_buggy"),
    Topology("fft", width=2, depth=2),
    Topology("fft", width=2, depth=2, fft_mode="rfft"),
    Topology("recurrent", width=2, depth=2),
], ids=["agg-avg", "agg-max", "agg-max_buggy", "fft", "fft-rfft", "rnn"])
def test_popmajor_variants_match_rowmajor(topo):
    """The k-vector and recurrent variants ride the lane layout too
    (ops/popmajor_kvec.py, ops/popmajor_rnn.py): full dynamics (attack +
    imitation + train + respawn) over several generations must track the
    row-major path under the shared PRNG stream."""
    cfg_row = SoupConfig(topo=topo, size=16, attacking_rate=0.4,
                         learn_from_rate=0.3, learn_from_severity=2, train=2,
                         remove_divergent=True, remove_zero=True)
    cfg_pop = cfg_row._replace(layout="popmajor")
    st = seed(cfg_row, jax.random.key(9))
    row_s, row_ev = evolve_step(cfg_row, st)
    pop_s, pop_ev = evolve_step(cfg_pop, st)
    np.testing.assert_array_equal(np.asarray(row_ev.action),
                                  np.asarray(pop_ev.action))
    np.testing.assert_array_equal(np.asarray(row_s.uids), np.asarray(pop_s.uids))
    np.testing.assert_allclose(np.asarray(row_s.weights),
                               np.asarray(pop_s.weights), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(row_ev.loss), np.asarray(pop_ev.loss),
                               rtol=1e-3, atol=1e-6)
    # multi-generation scan path agrees too
    row = evolve(cfg_row, st, generations=8)
    pop = evolve(cfg_pop, st, generations=8)
    np.testing.assert_array_equal(np.asarray(row.uids), np.asarray(pop.uids))
    np.testing.assert_allclose(np.asarray(row.weights), np.asarray(pop.weights),
                               rtol=1e-3, atol=1e-5)


# ----------------------------------------- parallel-vs-sequential statistics


def _class_counts_over_seeds(cfg, n_seeds, generations):
    """End-state class histograms for n_seeds independent soups, evolved in
    one vmapped/jitted program (soups stacked on a leading axis)."""
    states = [seed(cfg, jax.random.key(s)) for s in range(n_seeds)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    finals = jax.vmap(lambda s: evolve(cfg, s, generations=generations))(stacked)
    return np.stack([
        np.asarray(count(cfg, jax.tree.map(lambda x: x[i], finals)))
        for i in range(n_seeds)
    ])


def test_parallel_vs_sequential_distribution():
    """Quantifies the documented last-attacker-wins deviation (soup.py
    header vs reference soup.py:54-61): with respawn OFF, the parallel and
    sequential modes' end-state class-count distributions are statistically
    indistinguishable at the paper's rates (measured: largest per-class
    |dmean| = 0.25/100 particles, all |d|/SE < 1 at 20 seeds); with respawn
    ON the known TIMING deviation appears (sequential re-kills respawned
    particles later in the same generation, leaving ~1.35/100 divergent at
    count time where parallel leaves ~0).  PARITY.md L3 documents the
    measured numbers."""
    n_seeds, gens = 20, 100
    common = dict(size=100, attacking_rate=0.1, learn_from_rate=-1.0, train=0)

    # respawn OFF: isolates the collision/ordering deviation
    par = _class_counts_over_seeds(
        mkconfig(**common, mode="parallel"), n_seeds, gens)
    seq = _class_counts_over_seeds(
        mkconfig(**common, mode="sequential"), n_seeds, gens)
    delta = par.mean(0) - seq.mean(0)
    se = np.sqrt(par.var(0) / n_seeds + seq.var(0) / n_seeds)
    # indistinguishable: every class within 3 SE (and within 1 particle abs)
    assert (np.abs(delta) <= np.maximum(3 * se, 1.0)).all(), (delta, se)

    # respawn ON: the timing deviation is real, bounded, and directional
    par_r = _class_counts_over_seeds(
        mkconfig(**common, mode="parallel", remove_divergent=True,
                 remove_zero=True), n_seeds, gens)
    seq_r = _class_counts_over_seeds(
        mkconfig(**common, mode="sequential", remove_divergent=True,
                 remove_zero=True), n_seeds, gens)
    # parallel counts after end-of-generation respawn: ~no dead particles
    assert par_r.mean(0)[0] <= 0.2
    # sequential keeps a small residual divergent mass — present but < 4/100
    assert 0.0 < seq_r.mean(0)[0] < 4.0


# ------------------------------------------------- fused respawn draws


def test_fused_respawn_layouts_agree_and_law_is_bounded():
    """respawn_draws='fused' draws the SAME (P, N) replacement tensor for
    both layouts (row-major transposes it), so popmajor and rowmajor stay
    in lockstep; and every replacement obeys the per-weight glorot bound."""
    from srnn_tpu.init import _glorot_limit_rows, init_popmajor_fused

    dyn = dict(attacking_rate=0.5, learn_from_rate=-1.0, train=0,
               remove_divergent=True, remove_zero=True,
               respawn_draws="fused")
    cfg_row = mkconfig(size=24, **dyn)
    cfg_pop = mkconfig(size=24, layout="popmajor", **dyn)
    st = seed(cfg_row, jax.random.key(13))
    row = evolve(cfg_row, st, generations=12)
    pop = evolve(cfg_pop, st, generations=12)
    np.testing.assert_array_equal(np.asarray(row.uids), np.asarray(pop.uids))
    # the layouts reassociate the attack chain differently; 12 generations
    # at rate 0.5 compound that on diverged (1e18-magnitude) survivors, so
    # the tolerance is loose — the respawn-stream agreement this test is
    # about is pinned bitwise by the uid check above
    np.testing.assert_allclose(np.asarray(row.weights), np.asarray(pop.weights),
                               rtol=5e-3, atol=1e-5)
    assert int(row.next_uid) > 24  # respawns actually happened

    lim = _glorot_limit_rows(WW)
    draw = np.asarray(init_popmajor_fused(WW, jax.random.key(0), 1000))
    assert (np.abs(draw) <= lim[:, None] + 1e-7).all()
    # per-row spread uses each row's OWN limit (WW limits span 1.0..1.41,
    # so a global-bound bug would fail the larger rows' maxima here)
    assert (draw.max(axis=1) > 0.9 * lim).all()


def test_fused_respawn_rejected_in_sequential_parity_mode():
    cfg = mkconfig(mode="sequential", respawn_draws="fused",
                   remove_divergent=True)
    with pytest.raises(ValueError):
        evolve_step(cfg, seed(mkconfig(), jax.random.key(0)))


def test_fused_respawn_recurrent_falls_back_per_particle():
    """The recurrent variant's orthogonal kernels have no fused law; the
    fused flag silently keeps the per-particle draw for it (documented),
    so mixed soups can use 'fused' globally."""
    rnn = Topology("recurrent", width=2, depth=2)
    cfg = SoupConfig(topo=rnn, size=8, attacking_rate=0.5,
                     remove_divergent=True, remove_zero=True,
                     respawn_draws="fused")
    cfg_pp = cfg._replace(respawn_draws="perparticle")
    st = seed(cfg, jax.random.key(3))
    a = evolve(cfg, st, generations=10)
    b = evolve(cfg_pp, st, generations=10)
    np.testing.assert_array_equal(np.asarray(a.weights), np.asarray(b.weights))
