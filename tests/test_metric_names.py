"""Thin wrapper: the metric-name gate (AST registration scan against the
canonical ``telemetry.names`` table + convention check) now lives in the
srnnlint framework (``srnn_tpu/analysis/passes/metric_names.py``).  The
runtime halves — the ``EVENT_COUNTERS`` table and the ``ACTION_NAMES``
spelling that motivated the gate — stay here, since they only exist as
imported objects."""

import os

from srnn_tpu.analysis import AnalysisContext, run_analysis, select
from srnn_tpu.telemetry.names import CANONICAL_METRICS

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_metric_names_gate():
    ctx = AnalysisContext.from_root(REPO_ROOT)
    result = run_analysis(ctx, select(["metric-names"]))
    assert not result.errors, "\n".join(f.render() for f in result.errors)


def test_event_counter_table_is_canonical():
    from srnn_tpu.telemetry.soup_metrics import EVENT_COUNTERS

    for action, (name, _help) in EVENT_COUNTERS.items():
        assert CANONICAL_METRICS.get(name) == "counter", \
            f"EVENT_COUNTERS[{action!r}] -> {name!r} missing from the " \
            "canonical table"
        assert "zweo" not in action and "zweo" not in name


def test_action_names_spelling():
    """The rename satellite itself: the zero-respawn label is fixed, and
    the misspelling can never silently return."""
    from srnn_tpu.soup import ACTION_NAMES

    assert "zero_dead" in ACTION_NAMES
    assert "zweo_dead" not in ACTION_NAMES
