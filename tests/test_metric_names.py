"""Metric-name gate: every metric registered anywhere under ``srnn_tpu/``
must be declared in the canonical table (``telemetry.names``) with the
right kind and follow the naming convention — the collection-time
tripwire for the next ``zweo``-style drift.

Two halves:

  * **AST** — walk the package for ``.counter("…")`` / ``.gauge("…")`` /
    ``.histogram("…")`` calls with a literal name, including the
    ``g = registry.gauge; g("…")`` aliasing idiom the hot paths use.
  * **Registry** — the names that only exist as table entries
    (``soup_metrics.EVENT_COUNTERS``) are checked by importing the table.
"""

import ast
import os

from srnn_tpu.telemetry.names import CANONICAL_METRICS, check_name

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "srnn_tpu")

_KINDS = ("counter", "gauge", "histogram")


def _registrations(tree):
    """(kind, name, lineno) for every literal metric registration in one
    module, resolving single-letter aliases like ``g = registry.gauge``."""
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr in _KINDS:
            aliases[node.targets[0].id] = node.value.attr
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        arg0 = node.args[0]
        if not (isinstance(arg0, ast.Constant) and isinstance(arg0.value, str)):
            continue
        f = node.func
        kind = None
        if isinstance(f, ast.Attribute) and f.attr in _KINDS:
            kind = f.attr
        elif isinstance(f, ast.Name) and f.id in aliases:
            kind = aliases[f.id]
        if kind is not None:
            yield kind, arg0.value, node.lineno


def _package_registrations():
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, PKG).replace(os.sep, "/")
            with open(path) as f:
                tree = ast.parse(f.read(), filename=rel)
            for kind, name, lineno in _registrations(tree):
                yield rel, lineno, kind, name


def test_every_registered_name_is_canonical():
    problems = []
    seen = set()
    for rel, lineno, kind, name in _package_registrations():
        seen.add(name)
        declared = CANONICAL_METRICS.get(name)
        if declared is None:
            problems.append(
                f"{rel}:{lineno}: metric {name!r} not in "
                "telemetry.names.CANONICAL_METRICS — declare it (and check "
                "the spelling: this gate exists because of 'zweo_dead')")
        elif declared != kind:
            problems.append(
                f"{rel}:{lineno}: metric {name!r} registered as {kind}, "
                f"declared as {declared}")
    assert seen, "AST scan found no registrations — the gate is broken"
    assert not problems, "\n".join(problems)


def test_event_counter_table_is_canonical():
    from srnn_tpu.telemetry.soup_metrics import EVENT_COUNTERS

    for action, (name, _help) in EVENT_COUNTERS.items():
        assert CANONICAL_METRICS.get(name) == "counter", \
            f"EVENT_COUNTERS[{action!r}] -> {name!r} missing from the " \
            "canonical table"
        assert "zweo" not in action and "zweo" not in name


def test_canonical_names_follow_convention():
    problems = []
    for name, kind in CANONICAL_METRICS.items():
        assert kind in _KINDS, f"{name}: unknown kind {kind!r}"
        problems.extend(check_name(name, kind))
    assert not problems, "\n".join(problems)


def test_action_names_spelling():
    """The rename satellite itself: the zero-respawn label is fixed, and
    the misspelling can never silently return."""
    from srnn_tpu.soup import ACTION_NAMES

    assert "zero_dead" in ACTION_NAMES
    assert "zweo_dead" not in ACTION_NAMES
