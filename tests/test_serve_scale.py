"""Continuous-batching serve tier (PR 16): adaptive windows + the fleet.

Three contracts under test.  The CONTROLLER law: per-group windows start
at the floor, shrink multiplicatively under SLO burn, grow back toward
the ``--batch-window-s`` ceiling on clean dispatches — a pure fold over
the (group, violations) trace, so the same arrival trace always yields
the same windows.  The A/B ORACLE: with no controller attached the
dispatch path (and its metrics.prom) is the fixed-window PR 10 behavior
exactly.  The FLEET: N workers behind one front, sticky per-tenant
round-robin, the journal as the shared-nothing recovery substrate — a
worker killed mid-load strands nothing, because the front reads the
corpse's journal and replays its suffix onto the survivors, with results
bitwise-equal to a solo run of the same trace.

The in-process fleet tests stand a REAL ``ServiceServer`` per worker on
its own thread/root/socket (full dispatch path, no subprocess spawn
cost) behind a real ``ServicePool``; only the worker *process* handle is
faked.  The subprocess e2e (``--workers 2`` + SIGKILL) is marked
``slow``.
"""

import itertools
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from srnn_tpu.serve import (AdaptiveWindowController, ExperimentService,
                            Request, ServiceClient, ServicePool,
                            WorkerHandle, interleave_tenants,
                            make_controller, plan_dispatches, read_journal)
from srnn_tpu.serve.controller import DEFAULT_FLOOR_S
from srnn_tpu.serve.server import ServiceServer
from srnn_tpu.utils.pipeline import spawn_thread

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the one warm spelling every in-process test rides (compile paid once)
PARAMS = {"trials": 16, "batch": 16}
GROUP = ("fixpoint_density", None)


def _req(ticket, tenant, seed=0, kind="fixpoint_density"):
    return Request(ticket=ticket, kind=kind,
                   params=dict(PARAMS, seed=seed), tenant=tenant,
                   submitted_s=0.0)


# ---------------------------------------------------------------------------
# controller law: floor start, shrink under burn, grow on quiet, clamps
# ---------------------------------------------------------------------------


def test_controller_starts_at_floor_grows_clean_shrinks_burn():
    c = AdaptiveWindowController(ceiling_s=0.25, slo_p95_ms=100.0)
    assert c.window_s([GROUP]) == DEFAULT_FLOOR_S
    # clean dispatches grow multiplicatively toward the ceiling...
    last = DEFAULT_FLOOR_S
    for _ in range(40):
        cur = c.observe_dispatch(GROUP, violations=0, completed=4)
        assert cur >= last
        last = cur
    assert last == 0.25            # ...and clamp AT it
    # burn shrinks multiplicatively back down...
    for _ in range(40):
        cur = c.observe_dispatch(GROUP, violations=2, completed=4)
        assert cur <= last
        last = cur
    assert last == DEFAULT_FLOOR_S  # ...and clamps at the floor
    # an empty dispatch (everything expired) moves nothing
    assert c.observe_dispatch(GROUP, violations=0, completed=0) == last


def test_controller_windows_are_per_group_and_min_folds():
    c = AdaptiveWindowController(ceiling_s=0.25, slo_p95_ms=100.0)
    hot, cold = ("soup", "a"), ("soup", "b")
    for _ in range(10):
        c.observe_dispatch(cold, violations=0, completed=2)
    c.observe_dispatch(hot, violations=1, completed=2)
    assert c.window_s([cold]) > c.window_s([hot])
    # a mixed pending queue waits the MIN over its groups: the burning
    # group must not be held hostage to the quiet group's long window
    assert c.window_s([cold, hot]) == c.window_s([hot])
    snap = c.snapshot()
    assert snap["adaptive"] is True and snap["groups"] == 2
    assert snap["window_min_s"] <= snap["window_max_s"]


def test_controller_is_deterministic_over_a_trace():
    trace = [(GROUP, 0, 4), (GROUP, 1, 4), (("soup", "x"), 0, 2),
             (GROUP, 0, 4), (("soup", "x"), 3, 2), (GROUP, 0, 4)]
    def run():
        c = make_controller(0.25, 100.0)
        return [c.observe_dispatch(g, v, n) for g, v, n in trace]
    assert run() == run()


def test_make_controller_gates_on_adaptive():
    assert make_controller(0.25, 100.0, adaptive=False) is None
    assert make_controller(0.25, 0.0) is not None   # no SLO still adapts


# ---------------------------------------------------------------------------
# fairness: tenant interleave + cross-group round-robin emission
# ---------------------------------------------------------------------------


def test_interleave_tenants_breaks_the_hog():
    reqs = [_req(f"h{i}", "hog", seed=i) for i in range(6)] \
        + [_req("b0", "b", seed=10), _req("c0", "c", seed=11)]
    order = [r.ticket for r in interleave_tenants(reqs)]
    # round 0 carries every tenant once, in first-appearance order
    assert order[:3] == ["h0", "b0", "c0"]
    # within a tenant, submission order is preserved
    assert [t for t in order if t.startswith("h")] == \
        [f"h{i}" for i in range(6)]


def test_fair_plan_spreads_stack_slots_and_groups():
    keys = {"fixpoint_density": lambda p: (p["trials"], p["batch"])}
    reqs = [_req(f"h{i}", "hog", seed=i) for i in range(6)] \
        + [_req("b0", "b", seed=10), _req("c0", "c", seed=11)]
    unfair = plan_dispatches(reqs, keys, max_stack=4)
    fair = plan_dispatches(reqs, keys, max_stack=4, fair=True)
    # unfair: the hog owns the whole first stack
    assert {r.tenant for r in unfair[0].requests} == {"hog"}
    # fair: the first stack seats every waiting tenant
    assert {"b", "c"}.issubset({r.tenant for r in fair[0].requests})
    # fairness reorders WHO rides when, never the total membership
    assert sorted(r.ticket for d in fair for r in d.requests) == \
        sorted(r.ticket for d in unfair for r in d.requests)
    # cross-group round-robin: chunk 0 of each group before chunk 1 of any
    two_groups = [_req(f"a{i}", "t", seed=i) for i in range(8)] + \
        [Request(ticket=f"s{i}", kind="fixpoint_density",
                 params={"trials": 32, "batch": 8, "seed": i}, tenant="t",
                 submitted_s=0.0) for i in range(8)]
    plan = plan_dispatches(two_groups, keys, max_stack=4, fair=True)
    gids = [d.key for d in plan]
    assert gids == [(16, 16), (32, 8), (16, 16), (32, 8)]


# ---------------------------------------------------------------------------
# condvar admission: idle dispatcher blocks, first ticket wakes it
# ---------------------------------------------------------------------------


def test_wait_for_work_blocks_idle_and_wakes_on_submit(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"))
    with svc:
        t0 = time.monotonic()
        assert svc.wait_for_work(timeout_s=0.05) is False
        assert time.monotonic() - t0 >= 0.05   # really waited (no spin)

        def late_submit():
            time.sleep(0.1)
            svc.submit("fixpoint_density", dict(PARAMS, seed=0))

        th = spawn_thread(late_submit, name="late-submit")
        t0 = time.monotonic()
        assert svc.wait_for_work(timeout_s=30.0) is True
        # the admission SIGNALED the wait — it returned in ~0.1s, not 30
        assert time.monotonic() - t0 < 5.0
        th.join()
        assert svc.wait_for_work(timeout_s=0.0) is True  # pending short-cut
        svc.run_pending()

        # wake() unblocks without work (the stop/drain path)
        th = spawn_thread(lambda: (time.sleep(0.1), svc.wake()),
                          name="late-wake")
        t0 = time.monotonic()
        assert svc.wait_for_work(timeout_s=30.0) is False
        assert time.monotonic() - t0 < 5.0
        th.join()


def test_pending_groups_orders_unique(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"))
    with svc:
        svc.submit("fixpoint_density", dict(PARAMS, seed=0))
        svc.submit("fixpoint_density", dict(PARAMS, seed=1))
        svc.submit("fixpoint_density",
                   {"trials": 32, "batch": 8, "seed": 2})
        groups = svc.pending_groups()
        assert len(groups) == 2 and groups[0][0] == "fixpoint_density"
        svc.run_pending()
        assert svc.pending_groups() == []


# ---------------------------------------------------------------------------
# the adaptive service: controller wired into dispatch; the A/B oracle
# ---------------------------------------------------------------------------


def test_adaptive_service_grows_when_clean_shrinks_under_burn(tmp_path):
    # generous SLO: every dispatch is clean -> the group's window grows
    svc = ExperimentService(str(tmp_path / "svc"), slo_p95_ms=60000.0)
    with svc:
        ctrl = make_controller(0.25, 60000.0)
        svc.attach_controller(ctrl)
        gid = None
        for i in range(3):
            svc.submit("fixpoint_density", dict(PARAMS, seed=i))
            gid = gid or svc.pending_groups()[0]   # the REAL spelling key
            svc.run_pending()
        grown = ctrl.window_s([gid])
        assert grown > DEFAULT_FLOOR_S
        assert ctrl.snapshot()["window_max_s"] > DEFAULT_FLOOR_S
        st = svc.stats()
        assert st["dispatch"]["adaptive"] is True
        assert st["dispatch"]["fair_tenants"] is True
        assert st["dispatch"]["groups"] == 1
        # the adaptive-only fleet gauges registered (M005's sites)
        rows = st["metrics"]
        assert "srnn_serve_inflight_requests" in rows
        assert "srnn_serve_window_seconds" in rows
    # impossible SLO: every ticket violates -> synthetic burn, shrink
    svc2 = ExperimentService(str(tmp_path / "svc2"), slo_p95_ms=0.001)
    with svc2:
        ctrl2 = make_controller(0.25, 0.001)
        svc2.attach_controller(ctrl2)
        svc2.submit("fixpoint_density", dict(PARAMS, seed=0))
        gid = svc2.pending_groups()[0]
        for _ in range(6):   # pre-grow so the shrink has room to show
            ctrl2.observe_dispatch(gid, violations=0, completed=1)
        grown = ctrl2.window_s([gid])
        svc2.run_pending()
        assert ctrl2.window_s([gid]) < grown
        assert svc2.stats()["slo"]["violations"] >= 1


def test_no_adaptive_oracle_keeps_legacy_metrics_surface(tmp_path):
    """The fixed-window oracle must not grow NEW metric series: its
    metrics.prom stays byte-comparable against the PR 10 service."""
    svc = ExperimentService(str(tmp_path / "svc"))
    with svc:
        svc.submit("fixpoint_density", dict(PARAMS, seed=0))
        svc.run_pending(window_s=0.25)
        rows = svc.stats()["metrics"]
        assert svc.stats()["dispatch"] == {"adaptive": False}
        assert not any(k.startswith("srnn_serve_window_seconds")
                       for k in rows)
        assert not any(k.startswith("srnn_serve_inflight_requests")
                       for k in rows)


# ---------------------------------------------------------------------------
# the in-process fleet: real workers on threads, fake process handles
# ---------------------------------------------------------------------------


class _FakeProc:
    """Process-handle stand-in for an in-process worker thread (pid,
    poll/terminate/kill — what the pool's monitor and reaper touch)."""

    _pids = itertools.count(90001)

    def __init__(self):
        self.pid = next(self._pids)
        self.returncode = None

    def poll(self):
        return self.returncode

    def terminate(self):
        if self.returncode is None:
            self.returncode = 0

    def kill(self):
        if self.returncode is None:
            self.returncode = -9

    def wait(self, timeout=None):
        return self.returncode


class _Fleet:
    """N real ServiceServers (threads) behind one real ServicePool."""

    def __init__(self, tmp_path, n, window_s=0.05, windows=None,
                 max_queue=0):
        self.root = str(tmp_path)
        self.services, self.servers, self.threads, handles = [], [], [], []
        for i in range(n):
            wroot = os.path.join(self.root, "workers", f"w{i}")
            wsock = os.path.join(self.root, "workers", f"w{i}.sock")
            os.makedirs(os.path.dirname(wroot), exist_ok=True)
            svc = ExperimentService(wroot)
            w_s = windows[i] if windows else window_s
            server = ServiceServer(svc, wsock, batch_window_s=w_s)
            thread = spawn_thread(server.serve_until_shutdown,
                                  name=f"fleet-w{i}")
            ServiceClient(wsock).wait_until_up(30)
            self.services.append(svc)
            self.servers.append(server)
            self.threads.append(thread)
            handles.append(WorkerHandle(i, wroot, wsock, _FakeProc()))
        self.pool = ServicePool(self.root, handles, max_queue=max_queue)

    def kill_worker(self, i):
        """The crash: drain stops the worker WITHOUT dispatching its
        queue (tickets stay journaled-unfinished, exactly what SIGKILL
        leaves) and the socket disappears.  The fake process handle
        stays "running" so the pool's liveness monitor does not race the
        test's explicit death ladder."""
        self.servers[i].stop(drain=True)
        self.threads[i].join(timeout=60)
        self.services[i].close()

    def close(self):
        self.pool.close()
        for th in self.threads:
            th.join(timeout=60)
        for svc in self.services:
            svc.close()


def test_two_worker_fleet_matches_solo_bitwise(tmp_path):
    """The scale-out determinism contract: the same trace through a
    2-worker fleet and through a solo service yields identical results
    per ticket (executors are pure functions of the journaled params)."""
    trace = [("a", 0), ("b", 1), ("a", 2), ("c", 3), ("b", 4), ("c", 5)]
    fleet = _Fleet(tmp_path / "fleet", n=2)
    try:
        tickets = [fleet.pool.submit("fixpoint_density",
                                     dict(PARAMS, seed=s), tenant=t)
                   for t, s in trace]
        got = [fleet.pool.wait(t, timeout_s=240) for t in tickets]
        st = fleet.pool.stats()
        assert st["front"]["admitted"] == 6
        assert st["front"]["completed"] == 6
        assert st["front"]["workers"] == 2
        # sticky round-robin spread the TENANTS across both workers
        assert {fleet.pool._tenant_worker[t] for t, _ in trace} == {0, 1}
        assert fleet.pool.healthz()["ok"] is True
    finally:
        fleet.close()
    solo = ExperimentService(str(tmp_path / "solo"))
    with solo:
        ref_t = [solo.submit("fixpoint_density", dict(PARAMS, seed=s),
                             tenant=t) for t, s in trace]
        solo.run_pending()
        for entry, rt in zip(got, ref_t):
            assert entry["status"] == "done"
            assert entry["result"] == solo.poll(rt)["result"]


@pytest.mark.slow
def test_kill_worker_mid_load_replays_onto_survivor(tmp_path):
    """The acceptance chaos drill, in-process: tickets queued on worker
    0 (long window) when it dies; the front reads the corpse's journal,
    replays the suffix onto worker 1, and every acknowledged ticket
    completes.  healthz tells the loss (stranded on a dead worker) and
    then the heal."""
    fleet = _Fleet(tmp_path, n=2, windows=[30.0, 0.05])
    try:
        # tenant "a" lands sticky on w0 (first tenant, rr slot 0), where
        # the 30s window keeps the tickets queued — journaled, undone
        tickets = [fleet.pool.submit("fixpoint_density",
                                     dict(PARAMS, seed=i), tenant="a")
                   for i in range(3)]
        assert fleet.pool._tenant_worker["a"] == 0
        unfinished, _, _ = read_journal(
            os.path.join(fleet.root, "workers", "w0", "journal.jsonl"))
        assert [e.key for e in unfinished] == \
            [f"pool:{t}" for t in tickets]
        fleet.kill_worker(0)
        # the LOSS edge: dead worker, replay not yet run -> not ok
        with fleet.pool._lock:
            fleet.pool.workers[0].alive = False
        hz = fleet.pool.healthz()
        assert hz["ok"] is False and hz["stranded"] == 3
        with fleet.pool._lock:
            fleet.pool.workers[0].alive = True
        # the HEAL: the death ladder reads w0's journal, replays onto w1
        fleet.pool._note_death(0)
        hz = fleet.pool.healthz()
        assert hz["ok"] is True
        assert hz["deaths"] == 1 and hz["replayed"] == 3
        assert hz["workers"]["0"]["ok"] is False   # the corpse stays shown
        for t in tickets:
            entry = fleet.pool.wait(t, timeout_s=240)
            assert entry["status"] == "done"
        assert fleet.pool.registry.counter(
            "serve_worker_replays_total").value() == 3
        assert fleet.pool.registry.counter(
            "serve_worker_deaths_total").value() == 1
        assert fleet.pool.registry.gauge("serve_workers").value() == 1
        rows = [json.loads(l) for l in
                open(os.path.join(fleet.root, "events.jsonl"))]
        assert any(r.get("kind") == "pool_worker_death" for r in rows)
    finally:
        fleet.close()


def test_front_restart_recovers_its_journal(tmp_path):
    """kill -9 of the FRONT: admitted tickets are journaled
    durable-before-ack, so a fresh front on the same root replays and
    completes them under their original ids (idempotent re-forward —
    the workers dedupe on the ``pool:`` keys)."""
    fleet = _Fleet(tmp_path, n=1, window_s=0.05)
    try:
        tickets = [fleet.pool.submit("fixpoint_density",
                                     dict(PARAMS, seed=i), tenant="a")
                   for i in range(2)]
        # the front "crashes": no close, no drain — only its monitor
        # stops (a dead process polls nothing)
        fleet.pool._stop.set()
        fleet.pool._monitor.join(timeout=10)
        pool2 = ServicePool(fleet.root, fleet.pool.workers)
        try:
            assert pool2.recover() == 2
            for t in tickets:
                assert pool2.wait(t, timeout_s=240)["status"] == "done"
            # the watermark survived: fresh ids continue past replayed
            assert pool2.submit("fixpoint_density", dict(PARAMS, seed=9),
                                tenant="a") == "t000003"
            assert pool2.wait("t000003", timeout_s=240)["status"] == "done"
        finally:
            pool2.close()
            fleet.pool.journal.close()
            with fleet.pool._events_lock:
                fleet.pool._events.close()
    finally:
        for th in fleet.threads:
            th.join(timeout=60)
        for svc in fleet.services:
            svc.close()


# ---------------------------------------------------------------------------
# subprocess e2e (slow): --workers 2, SIGKILL one worker under load
# ---------------------------------------------------------------------------


def _serve_env():
    env = dict(os.environ)
    env["SRNN_SETUPS_PLATFORM"] = "cpu"
    env.pop("PYTHONPATH", None)
    return env


@pytest.mark.slow
def test_fleet_e2e_sigkill_worker_under_load(tmp_path):
    root = str(tmp_path / "fleet")
    log = open(str(tmp_path / "fleet.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "srnn_tpu.serve", "--root", root,
         "--workers", "2", "--batch-window-s", "0.25",
         "--slo-p95-ms", "2000"],
        cwd=REPO, env=_serve_env(), stdout=log,
        stderr=subprocess.STDOUT)
    try:
        client = ServiceClient(os.path.join(root, "serve.sock"),
                               retries=3, backoff_base_s=0.1)
        client.wait_until_up(240)
        tickets = [client.submit("fixpoint_density", dict(PARAMS, seed=s),
                                 tenant=f"tn{s % 4}",
                                 idempotency_key=f"fe2e-{s}")
                   for s in range(8)]
        stats = client.stats()
        assert stats["front"]["workers"] == 2
        victim = stats["fleet"]["w0"]["pid"]
        os.kill(victim, signal.SIGKILL)
        # every acknowledged ticket still completes (the survivors
        # absorb the dead worker's journal suffix)
        for t in tickets:
            assert client.wait(t, timeout_s=300) is not None
        stats = client.stats()
        assert stats["front"]["deaths"] == 1
        assert stats["front"]["workers"] == 1
        assert stats["front"]["completed"] == 8
        client.shutdown()
        assert proc.wait(timeout=120) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
    prom = open(os.path.join(root, "metrics.prom")).read()
    assert "srnn_serve_worker_deaths_total 1" in prom
