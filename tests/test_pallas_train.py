"""Round-5 fused Pallas SGD kernels: recurrent (BPTT), k-vector
(aggregating/fft), and nonlinear weightwise — parity vs the XLA popmajor
paths in interpret mode on CPU, plus dispatch/fence behavior.

(The original weightwise-linear kernel's tests live in test_pallas_ww.py;
this file covers the round-5 extension to every variant.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology, init_population
from srnn_tpu.ops.pallas_kvec_train import (kvec_learn_epochs_pallas,
                                            kvec_train_epochs_pallas)
from srnn_tpu.ops.pallas_rnn_train import (rnn_learn_epochs_pallas,
                                           rnn_train_epochs_pallas)
from srnn_tpu.ops.popmajor import resolved_train_impl
from srnn_tpu.ops.popmajor_kvec import (kvec_learn_epochs_popmajor,
                                        kvec_train_epochs_popmajor)
from srnn_tpu.ops.popmajor_rnn import (rnn_learn_epochs_popmajor,
                                       rnn_train_epochs_popmajor)


def _pop(topo, seed, n=24):
    return (init_population(topo, jax.random.key(seed), n) * 0.3).T


# ------------------------------------------------------------- recurrent


@pytest.mark.parametrize("activation", ["linear", "tanh", "relu"])
def test_rnn_kernel_matches_xla_bptt(activation):
    """The hand-derived BPTT reproduces jax.grad through the time scan —
    weights have matched BITWISE on CPU; the assert keeps float headroom."""
    topo = Topology("recurrent", activation=activation)
    wT = _pop(topo, 0)
    ref_w, ref_l = rnn_train_epochs_popmajor(topo, wT, 3)
    got_w, got_l = rnn_train_epochs_pallas(topo, wT, 3, interpret=True)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref_l),
                               rtol=1e-5, atol=1e-6)


def test_rnn_kernel_learn_matches_xla():
    topo = Topology("recurrent")
    wT, other = _pop(topo, 0), _pop(topo, 1)
    ref_w, ref_l = rnn_learn_epochs_popmajor(topo, wT, other, 2)
    got_w, got_l = rnn_learn_epochs_pallas(topo, wT, other, 2, interpret=True)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref_l),
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------- k-vector


@pytest.mark.parametrize("topo", [
    Topology("aggregating"),
    Topology("aggregating", aggregator="max_buggy"),
    Topology("aggregating", activation="sigmoid"),
    Topology("aggregating", activation="relu"),
    Topology("fft"),
    Topology("fft", fft_mode="rfft"),
], ids=["agg-avg", "agg-maxbuggy", "agg-sigmoid", "agg-relu", "fft", "rfft"])
def test_kvec_kernel_matches_xla(topo):
    wT = _pop(topo, 0)
    ref_w, ref_l = kvec_train_epochs_popmajor(topo, wT, 3)
    got_w, got_l = kvec_train_epochs_pallas(topo, wT, 3, interpret=True)
    # fft rows compare a cos-basis chain against jnp.fft — float noise only
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref_l),
                               rtol=1e-4, atol=1e-6)


def test_kvec_kernel_nonfinite_propagation_matches_xla():
    """Non-finite weights must propagate through the kernel's reduction
    exactly as through the XLA path's one-hot matmul: an Inf weight
    poisons every OTHER aggregate with NaN (0*Inf) but enters its OWN
    segment's sum at full value (Inf stays Inf) — neither a per-segment
    add chain (confines it) nor one shared poison term (NaNs the home
    segment too) reproduces both halves (round-5 review repros)."""
    from srnn_tpu.ops.pallas_kvec_train import _reduce_rows
    from srnn_tpu.ops.popmajor_kvec import kvec_reduce_popmajor

    topo = Topology("aggregating")
    wT = _pop(topo, 0, n=8)
    wT = wT.at[3, 2].set(jnp.inf)  # row 3 is INSIDE segment 0 (P=14, k=4)
    ref_k = np.asarray(kvec_reduce_popmajor(topo, wT))
    got_k = np.asarray(jnp.stack(
        _reduce_rows(topo, tuple(wT[r] for r in range(wT.shape[0])))))
    assert np.isinf(ref_k[0, 2]) and np.isnan(ref_k[1:, 2]).all()
    np.testing.assert_array_equal(np.isinf(ref_k), np.isinf(got_k))
    np.testing.assert_array_equal(np.isnan(ref_k), np.isnan(got_k))

    ref_w, ref_l = kvec_train_epochs_popmajor(topo, wT, 2)
    got_w, got_l = kvec_train_epochs_pallas(topo, wT, 2, interpret=True)
    np.testing.assert_array_equal(np.isnan(np.asarray(ref_w)),
                                  np.isnan(np.asarray(got_w)))
    np.testing.assert_array_equal(np.isnan(np.asarray(ref_l)),
                                  np.isnan(np.asarray(got_l)))
    fin = np.isfinite(np.asarray(ref_w))
    np.testing.assert_allclose(np.asarray(got_w)[fin],
                               np.asarray(ref_w)[fin], rtol=1e-5, atol=1e-6)


def test_kvec_kernel_learn_matches_xla():
    topo = Topology("aggregating")
    wT, other = _pop(topo, 0), _pop(topo, 1)
    ref_w, ref_l = kvec_learn_epochs_popmajor(topo, wT, other, 2)
    got_w, got_l = kvec_learn_epochs_pallas(topo, wT, other, 2,
                                            interpret=True)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref_l),
                               rtol=1e-4, atol=1e-6)


# ------------------------------------------- nonlinear weightwise (round 5)


@pytest.mark.parametrize("activation", ["sigmoid", "relu"])
def test_ww_kernel_nonlinear_matches_xla(activation):
    from srnn_tpu.ops.pallas_ww_train import ww_train_epochs_pallas
    from srnn_tpu.ops.popmajor import ww_train_epochs_popmajor

    topo = Topology("weightwise", activation=activation)
    wT = _pop(topo, 0)
    ref_w, ref_l = ww_train_epochs_popmajor(topo, wT, 3)
    got_w, got_l = ww_train_epochs_pallas(topo, wT, 3, interpret=True)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref_l),
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------- fused recurrent APPLY


@pytest.mark.parametrize("activation", ["linear", "tanh"])
def test_rnn_apply_kernel_matches_xla(activation):
    from srnn_tpu.ops.pallas_rnn_apply import rnn_apply_pallas
    from srnn_tpu.ops.popmajor_rnn import rnn_forward_popmajor

    topo = Topology("recurrent", activation=activation)
    selfT, targetT = _pop(topo, 0), _pop(topo, 1)
    ref = rnn_forward_popmajor(topo, selfT, targetT)
    got = rnn_apply_pallas(topo, selfT, targetT, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-7)


def test_rnn_apply_kernel_cross_shape():
    """Cross-architecture attack: a recurrent attacker consumes a victim
    sequence of a DIFFERENT length (the victim topology's weight count)."""
    from srnn_tpu.ops.pallas_rnn_apply import rnn_apply_pallas
    from srnn_tpu.ops.popmajor_cross import cross_apply_popmajor

    atk = Topology("recurrent")
    vic = Topology("weightwise", width=3)  # P=24 != atk's 17
    selfT = _pop(atk, 0)
    targetT = _pop(vic, 1)
    ref = cross_apply_popmajor(atk, selfT, vic, targetT)
    got = cross_apply_popmajor(atk, selfT, vic, targetT, impl="pallas")
    assert got.shape == targetT.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-7)


def test_rnn_apply_big_victim_falls_back():
    """Cross-type pallas apply must bound the VICTIM's weight count too:
    the kernel unrolls T = P_victim timesteps, so a big victim silently
    takes the XLA scan instead of compiling forever (round-5 review
    finding)."""
    from srnn_tpu.ops.popmajor import _use_pallas_apply

    atk = Topology("recurrent")
    assert _use_pallas_apply(atk, "pallas", target_p=17)
    assert not _use_pallas_apply(atk, "pallas", target_p=104)


def test_rnn_apply_soup_parity_and_fences():
    from srnn_tpu.soup import SoupConfig, evolve, evolve_step, seed

    topo = Topology("recurrent")
    cfg_x = SoupConfig(topo=topo, size=12, attacking_rate=0.5,
                       remove_divergent=True, remove_zero=True,
                       layout="popmajor")
    cfg_p = cfg_x._replace(apply_impl="pallas")
    st = seed(cfg_x, jax.random.key(4))
    ref = evolve(cfg_x, st, generations=4)
    got = evolve(cfg_p, st, generations=4)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(got.uids))
    ref_w, got_w = np.asarray(ref.weights), np.asarray(got.weights)
    fin = np.isfinite(ref_w)
    assert (fin == np.isfinite(got_w)).all()
    np.testing.assert_allclose(got_w[fin], ref_w[fin], rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="apply_impl"):  # non-recurrent
        ww = Topology("weightwise")
        cfg = SoupConfig(topo=ww, size=8, layout="popmajor",
                         apply_impl="pallas")
        evolve_step(cfg, seed(cfg._replace(apply_impl="xla"),
                              jax.random.key(0)))
    with pytest.raises(ValueError, match="rowmajor"):
        cfg = cfg_p._replace(layout="rowmajor")
        evolve_step(cfg, st)
    with pytest.raises(ValueError, match="compact"):
        evolve_step(cfg_p._replace(attack_impl="compact"), st)


# ------------------------------------------------- soup-level integration


@pytest.mark.parametrize("topo", [
    Topology("recurrent"),
    Topology("aggregating"),
], ids=["recurrent", "aggregating"])
def test_pallas_train_soup_parity(topo):
    """A full-dynamics popmajor soup with train_impl='pallas' tracks the
    XLA-path soup for the newly covered variants."""
    from srnn_tpu.soup import SoupConfig, evolve, seed

    cfg_x = SoupConfig(topo=topo, size=10, attacking_rate=0.4,
                       learn_from_rate=0.3, learn_from_severity=1, train=2,
                       remove_divergent=True, remove_zero=True,
                       layout="popmajor")
    cfg_p = cfg_x._replace(train_impl="pallas")
    st = seed(cfg_x, jax.random.key(2))
    ref = evolve(cfg_x, st, generations=3)
    got = evolve(cfg_p, st, generations=3)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(got.uids))
    ref_w, got_w = np.asarray(ref.weights), np.asarray(got.weights)
    finite = np.isfinite(ref_w)
    assert (finite == np.isfinite(got_w)).all()
    np.testing.assert_allclose(got_w[finite], ref_w[finite],
                               rtol=1e-4, atol=1e-5)


def test_multisoup_resolves_all_types_to_pallas():
    """The heterogeneous multisoup's per-type resolution now takes the
    kernel for every science-default variant (round-4 advisor finding:
    silent per-type fallback must at least be reportable)."""
    for topo in [Topology("weightwise"), Topology("aggregating"),
                 Topology("fft"), Topology("recurrent")]:
        assert resolved_train_impl(topo, "sequential", "pallas") == "pallas"
    # still-fenced cases resolve to xla (reported, not raised, per-type)
    assert resolved_train_impl(
        Topology("weightwise", activation="elu"), "sequential",
        "pallas") == "xla"
    assert resolved_train_impl(
        Topology("weightwise"), "full_batch", "pallas") == "xla"


def test_multisoup_big_member_falls_back_not_raises():
    """A >64-weight member under train_impl='pallas' must EXECUTE the
    silent per-type XLA fallback that resolved_train_impl reports — the
    dispatch raising here would make report and run disagree (round-5
    review finding)."""
    from srnn_tpu.ops.popmajor import train_epochs_popmajor

    big = Topology("weightwise", width=8, depth=2)  # P=104 > the 64 fence
    assert big.num_weights > 64
    assert resolved_train_impl(big, "sequential", "pallas") == "xla"
    # 'pallas' silently executes the XLA path with an identical result —
    # this is the exact dispatch call the multisoup's per-type train phase
    # makes (a full P=104 evolve_multi_step compile takes >10 min on the
    # shared CPU core, so the end-to-end leg is not exercised here)
    wT = _pop(big, 0, n=8)
    ref = train_epochs_popmajor(big, wT, 1, impl="xla")
    got = train_epochs_popmajor(big, wT, 1, impl="pallas")
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(ref[0]))


def test_pallas_fences():
    from srnn_tpu.soup import SoupConfig, evolve_step, seed

    base = dict(size=8, train=1, layout="popmajor", train_impl="pallas")
    # activations without an output-expressible derivative stay XLA-only
    elu = Topology("recurrent", activation="elu")
    cfg = SoupConfig(topo=elu, **base)
    with pytest.raises(ValueError, match="train_impl='pallas'"):
        evolve_step(cfg, seed(cfg._replace(train_impl="xla"),
                              jax.random.key(0)))
    # weightwise full_batch is a different program — kernel refuses
    wwfb = SoupConfig(topo=Topology("weightwise"), train_mode="full_batch",
                      **base)
    with pytest.raises(ValueError, match="sequential"):
        evolve_step(wwfb, seed(wwfb._replace(train_impl="xla"),
                               jax.random.key(0)))
    # recurrent full_batch coincides with sequential — ACCEPTED
    rnnfb = SoupConfig(topo=Topology("recurrent"), train_mode="full_batch",
                       **base)
    st = seed(rnnfb._replace(train_impl="xla"), jax.random.key(0))
    evolve_step(rnnfb, st)  # must not raise
    # particle-size fence raises (never silently compiles forever)
    big = Topology("recurrent", width=8, depth=2)
    assert big.num_weights > 64
    cfg_big = SoupConfig(topo=big, **base)
    with pytest.raises(ValueError, match="64"):
        evolve_step(cfg_big, seed(cfg_big._replace(train_impl="xla"),
                                  jax.random.key(0)))
