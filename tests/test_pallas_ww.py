"""Pallas weightwise population kernel vs the reference vmap path
(interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology, init_population
from srnn_tpu.nets import apply_to_weights
from srnn_tpu.ops.pallas_ww import ww_apply_population, ww_apply_population_jnp
from tests.test_apply import WW, identity_fixpoint_flat


@pytest.mark.parametrize("activation", ["linear", "sigmoid"])
def test_kernel_matches_vmap(activation):
    topo = Topology("weightwise", activation=activation)
    pop = init_population(topo, jax.random.key(0), 64) * 0.3
    ref = jax.vmap(lambda w: apply_to_weights(topo, w, w))(pop)
    out = ww_apply_population(topo, pop.T, interpret=True)
    np.testing.assert_allclose(np.asarray(out.T), np.asarray(ref), rtol=1e-5, atol=1e-7)


def test_kernel_multi_step_chains():
    pop = init_population(WW, jax.random.key(1), 16) * 0.05
    ref = pop
    for _ in range(4):
        ref = jax.vmap(lambda w: apply_to_weights(WW, w, w))(ref)
    out = ww_apply_population(WW, pop.T, steps=4, interpret=True)
    np.testing.assert_allclose(np.asarray(out.T), np.asarray(ref), rtol=1e-5, atol=1e-7)


def test_kernel_identity_fixpoint_exact():
    ident = jnp.asarray(identity_fixpoint_flat())
    wT = jnp.tile(ident[:, None], (1, 8))
    out = ww_apply_population(WW, wT, steps=10, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(wT))


def test_kernel_pads_ragged_population():
    # N not a multiple of the lane block
    pop = init_population(WW, jax.random.key(2), 37) * 0.3
    ref = jax.vmap(lambda w: apply_to_weights(WW, w, w))(pop)
    out = ww_apply_population(WW, pop.T, interpret=True)
    assert out.shape == (14, 37)
    np.testing.assert_allclose(np.asarray(out.T), np.asarray(ref), rtol=1e-5, atol=1e-7)


def test_jnp_fallback_matches_vmap():
    pop = init_population(WW, jax.random.key(3), 50) * 0.3
    ref = jax.vmap(lambda w: apply_to_weights(WW, w, w))(pop)
    out = ww_apply_population_jnp(WW, pop.T)
    np.testing.assert_allclose(np.asarray(out.T), np.asarray(ref), rtol=1e-5, atol=1e-7)
