"""Pallas weightwise population kernel vs the reference vmap path
(interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology, init_population
from srnn_tpu.nets import apply_to_weights
from srnn_tpu.ops.pallas_ww import ww_apply_population, ww_apply_population_jnp
from tests.test_apply import WW, identity_fixpoint_flat


@pytest.mark.parametrize("activation", ["linear", "sigmoid"])
def test_kernel_matches_vmap(activation):
    topo = Topology("weightwise", activation=activation)
    pop = init_population(topo, jax.random.key(0), 64) * 0.3
    ref = jax.vmap(lambda w: apply_to_weights(topo, w, w))(pop)
    out = ww_apply_population(topo, pop.T, interpret=True)
    np.testing.assert_allclose(np.asarray(out.T), np.asarray(ref), rtol=1e-5, atol=1e-7)


def test_kernel_multi_step_chains():
    pop = init_population(WW, jax.random.key(1), 16) * 0.05
    ref = pop
    for _ in range(4):
        ref = jax.vmap(lambda w: apply_to_weights(WW, w, w))(ref)
    out = ww_apply_population(WW, pop.T, steps=4, interpret=True)
    np.testing.assert_allclose(np.asarray(out.T), np.asarray(ref), rtol=1e-5, atol=1e-7)


def test_kernel_identity_fixpoint_exact():
    ident = jnp.asarray(identity_fixpoint_flat())
    wT = jnp.tile(ident[:, None], (1, 8))
    out = ww_apply_population(WW, wT, steps=10, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(wT))


def test_kernel_pads_ragged_population():
    # N not a multiple of the lane block
    pop = init_population(WW, jax.random.key(2), 37) * 0.3
    ref = jax.vmap(lambda w: apply_to_weights(WW, w, w))(pop)
    out = ww_apply_population(WW, pop.T, interpret=True)
    assert out.shape == (14, 37)
    np.testing.assert_allclose(np.asarray(out.T), np.asarray(ref), rtol=1e-5, atol=1e-7)


def test_jnp_fallback_matches_vmap():
    pop = init_population(WW, jax.random.key(3), 50) * 0.3
    ref = jax.vmap(lambda w: apply_to_weights(WW, w, w))(pop)
    out = ww_apply_population_jnp(WW, pop.T)
    np.testing.assert_allclose(np.asarray(out.T), np.asarray(ref), rtol=1e-5, atol=1e-7)


# --------------------------------------------- fused sequential-SGD kernel


def test_pallas_train_matches_xla_chain():
    """The hand-derived linear backward reproduces jax.grad's batch-1
    sequential chain (ops/popmajor._ww_seq_sgd_flat) to float tolerance."""
    from srnn_tpu.ops.pallas_ww_train import (ww_learn_epochs_pallas,
                                              ww_train_epochs_pallas)
    from srnn_tpu.ops.popmajor import (ww_learn_epochs_popmajor,
                                       ww_train_epochs_popmajor)

    # width=3 exercises a non-default shape; P stays small — interpret-mode
    # compile time grows superlinearly in the chain length (P^2 per epoch)
    topo = Topology("weightwise", width=3, depth=2)
    wT = (init_population(topo, jax.random.key(0), 40) * 0.3).T
    ref_w, ref_l = ww_train_epochs_popmajor(topo, wT, 3)
    got_w, got_l = ww_train_epochs_pallas(topo, wT, 3, interpret=True)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref_l),
                               rtol=1e-5, atol=1e-6)

    other = (init_population(topo, jax.random.key(1), 40) * 0.3).T
    ref_w, ref_l = ww_learn_epochs_popmajor(topo, wT, other, 2)
    got_w, got_l = ww_learn_epochs_pallas(topo, wT, other, 2, interpret=True)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref_l),
                               rtol=1e-5, atol=1e-6)


def test_pallas_train_soup_parity_and_fences():
    """A full-dynamics popmajor soup with train_impl='pallas' tracks the
    XLA-path soup; unsupported configs are rejected upfront."""
    import pytest

    from srnn_tpu.soup import SoupConfig, evolve, evolve_step, seed

    topo = Topology("weightwise", width=2, depth=2)
    cfg_x = SoupConfig(topo=topo, size=12, attacking_rate=0.4,
                       learn_from_rate=0.3, learn_from_severity=1, train=2,
                       remove_divergent=True, remove_zero=True,
                       layout="popmajor")
    cfg_p = cfg_x._replace(train_impl="pallas")
    st = seed(cfg_x, jax.random.key(2))
    ref = evolve(cfg_x, st, generations=4)
    got = evolve(cfg_p, st, generations=4)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(got.uids))
    np.testing.assert_allclose(np.asarray(ref.weights),
                               np.asarray(got.weights), rtol=1e-4, atol=1e-5)

    with pytest.raises(ValueError):  # rowmajor never reaches the kernel
        evolve_step(cfg_p._replace(layout="rowmajor"), st)
    with pytest.raises(ValueError):  # full_batch has no sequential chain
        evolve_step(cfg_p._replace(train_mode="full_batch"), st)
    # sigmoid/tanh/relu are covered since round 5 (output-expressible
    # derivatives); activations outside that set still fence
    elu = Topology("weightwise", width=2, depth=2, activation="elu")
    with pytest.raises(ValueError):
        evolve_step(cfg_p._replace(topo=elu), seed(cfg_x._replace(topo=elu),
                                                   jax.random.key(0)))
