"""Training semantics: keras fit(batch_size=1) parity via lax.scan
(network.py:613-626, SURVEY §2.4.10)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology, apply_to_weights, compute_samples, init_flat, is_fixpoint
from srnn_tpu.train import fit_epoch, learn_from, predict, train_step
from tests.test_apply import WW, AGG, FFT, RNN


def np_sequential_sgd_ww(flat, lr=0.01):
    """Hand-rolled batch-1 SGD epoch for the linear weightwise net."""
    from srnn_tpu.topology import normalized_weight_coords
    from srnn_tpu.ops.flatten import unflatten

    coords = normalized_weight_coords(WW)
    x = np.concatenate([flat[:, None], coords], axis=1).astype(np.float64)
    y = flat.astype(np.float64).copy()
    w = flat.astype(np.float64).copy()
    losses = []
    for i in range(x.shape[0]):
        mats = [np.asarray(m, np.float64) for m in unflatten(WW, jnp.asarray(w.astype(np.float32)))]
        # forward with intermediates
        h = [x[i : i + 1]]
        for m in mats:
            h.append(h[-1] @ m)
        pred = h[-1][0, 0]
        loss = (pred - y[i]) ** 2
        losses.append(loss)
        # backward
        g_out = 2.0 * (pred - y[i])  # dL/dpred
        grad_mats = [np.zeros_like(m) for m in mats]
        gh = np.array([[g_out]])
        for li in reversed(range(len(mats))):
            grad_mats[li] = h[li].T @ gh
            gh = gh @ mats[li].T
        gflat = np.concatenate([g.ravel() for g in grad_mats])
        w = w - lr * gflat
    return w.astype(np.float32), float(np.mean(losses))


def test_ww_sequential_epoch_matches_numpy_backprop():
    rng = np.random.default_rng(0)
    flat = (rng.normal(size=14) * 0.5).astype(np.float32)
    expected_w, expected_loss = np_sequential_sgd_ww(flat)
    got_w, got_loss = train_step(WW, jnp.asarray(flat))
    np.testing.assert_allclose(np.asarray(got_w), expected_w, rtol=1e-4, atol=1e-6)
    assert float(got_loss) == pytest.approx(expected_loss, rel=1e-4)


def test_sequential_does_n_updates_full_batch_does_one():
    rng = np.random.default_rng(1)
    flat = jnp.asarray((rng.normal(size=14) * 0.5).astype(np.float32))
    seq_w, _ = train_step(WW, flat, mode="sequential")
    fb_w, _ = train_step(WW, flat, mode="full_batch")
    # both must move the weights, and differently (different semantics)
    assert not np.allclose(np.asarray(seq_w), np.asarray(flat))
    assert not np.allclose(np.asarray(fb_w), np.asarray(flat))
    assert not np.allclose(np.asarray(seq_w), np.asarray(fb_w))


def test_self_training_approaches_fixpoint():
    """1000 self-train epochs drive a WW net to a non-trivial fixpoint —
    the headline result of training-fixpoints.py (BASELINE.md: 50/50
    fix_other)."""
    flat = init_flat(WW, jax.random.key(7))

    @jax.jit
    def epochs(w):
        def body(x, _):
            new_x, loss = train_step(WW, x)
            return new_x, loss
        return jax.lax.scan(body, w, None, length=1000)

    w, losses = epochs(flat)
    f = functools.partial(apply_to_weights, WW, w)
    assert bool(is_fixpoint(f, w, epsilon=1e-4))
    assert float(losses[-1]) < float(losses[0])
    # non-trivial: not the zero fixpoint
    assert float(jnp.abs(w).max()) > 1e-4


def test_learn_from_moves_toward_other():
    rng = np.random.default_rng(3)
    a = jnp.asarray((rng.normal(size=14) * 0.5).astype(np.float32))
    b = jnp.asarray((rng.normal(size=14) * 0.5).astype(np.float32))
    x, y = compute_samples(WW, b)
    before = float(jnp.mean((predict(WW, a, x) - y.reshape(-1, 1)) ** 2))
    new_a, _ = learn_from(WW, a, b)
    after = float(jnp.mean((predict(WW, new_a, x) - y.reshape(-1, 1)) ** 2))
    assert after < before


@pytest.mark.parametrize("topo", [WW, AGG, FFT, RNN])
def test_train_step_all_variants_finite(topo):
    flat = init_flat(topo, jax.random.key(11)) * 0.3
    new_flat, loss = train_step(topo, flat)
    assert new_flat.shape == flat.shape
    assert np.isfinite(float(loss))
    assert np.all(np.isfinite(np.asarray(new_flat)))


def test_shuffled_epoch_is_permutation_of_updates():
    rng = np.random.default_rng(5)
    flat = jnp.asarray((rng.normal(size=14) * 0.5).astype(np.float32))
    w1, _ = train_step(WW, flat, key=jax.random.key(0))
    w2, _ = train_step(WW, flat, key=jax.random.key(1))
    w3, _ = train_step(WW, flat)
    # different orders give (slightly) different results but same magnitude
    assert not np.allclose(np.asarray(w1), np.asarray(w2))
    assert np.linalg.norm(np.asarray(w1) - np.asarray(w3)) < 0.1


# ------------------------------------------------- population-major trainer


@pytest.mark.parametrize("mode", ["sequential", "full_batch"])
def test_popmajor_fit_epoch_matches_rowmajor(mode):
    """ops.popmajor epoch == vmapped train.fit_epoch on the transposed pop."""
    from srnn_tpu.ops.popmajor import ww_fit_epoch_popmajor
    from srnn_tpu.nets import compute_samples

    topo = Topology("weightwise", width=2, depth=2)
    rng = np.random.default_rng(29)
    pop = jnp.asarray(rng.normal(size=(32, topo.num_weights)).astype(np.float32) * 0.5)

    def row_one(w):
        x, y = compute_samples(topo, w)
        return fit_epoch(topo, w, x, y, mode=mode)

    want_w, want_l = jax.vmap(row_one)(pop)
    got_wT, got_l = ww_fit_epoch_popmajor(topo, pop.T, pop.T, pop.T, mode=mode)
    np.testing.assert_allclose(np.asarray(got_wT.T), np.asarray(want_w),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(want_l),
                               rtol=2e-3, atol=1e-6)


def test_popmajor_train_epochs_recompute_samples():
    """Repeated train() recomputes samples from current weights each epoch —
    popmajor must match the row-major multi-epoch trajectory, not a frozen-
    sample one."""
    from srnn_tpu.ops.popmajor import ww_train_epochs_popmajor

    topo = Topology("weightwise", width=2, depth=2)
    rng = np.random.default_rng(31)
    pop = jnp.asarray(rng.normal(size=(8, topo.num_weights)).astype(np.float32) * 0.5)

    def row_epochs(w):
        for _ in range(4):
            w, loss = train_step(topo, w)
        return w, loss

    want_w, want_l = jax.vmap(row_epochs)(pop)
    got_wT, got_l = ww_train_epochs_popmajor(topo, pop.T, epochs=4)
    np.testing.assert_allclose(np.asarray(got_wT.T), np.asarray(want_w),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(want_l),
                               rtol=2e-3, atol=1e-6)


@pytest.mark.parametrize("variant", ["weightwise", "aggregating", "fft", "recurrent"])
def test_fit_epochs_flat_matches_repeated_calls(variant):
    """The flattened epochs*samples scan == the naive loop of train_step /
    learn_from calls, for every variant (same update order, same last-epoch
    keras-history loss)."""
    from srnn_tpu.train import fit_epochs_flat, learn_from
    from srnn_tpu.nets import compute_samples

    topo = Topology(variant, width=2, depth=2)
    rng = np.random.default_rng(37)
    w0 = jnp.asarray(rng.normal(size=topo.num_weights).astype(np.float32) * 0.4)
    other = jnp.asarray(rng.normal(size=topo.num_weights).astype(np.float32) * 0.4)

    # self-training: 3 repeated train() calls
    w_ref = w0
    for _ in range(3):
        w_ref, loss_ref = train_step(topo, w_ref)
    w_got, loss_got = fit_epochs_flat(topo, w0, epochs=3)
    np.testing.assert_allclose(np.asarray(w_got), np.asarray(w_ref),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(float(loss_got), float(loss_ref),
                               rtol=1e-4, atol=1e-8)

    # imitation: 2 repeated learn_from(other) calls (fixed samples)
    w_ref = w0
    for _ in range(2):
        w_ref, loss_ref = learn_from(topo, w_ref, other)
    x, y = compute_samples(topo, other)
    w_got, loss_got = fit_epochs_flat(topo, w0, epochs=2, xy=(x, y))
    np.testing.assert_allclose(np.asarray(w_got), np.asarray(w_ref),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(float(loss_got), float(loss_ref),
                               rtol=1e-4, atol=1e-8)
