"""Fused soup-generation megakernel (``generation_impl='fused'``,
``ops/pallas_generation.py``) and the bf16 population mode.

Contracts under test:

  * ``generation_impl='fused'`` at f32 is BIT-identical to the default
    phase-chain path — population state, uids, events, and the
    metrics/health/lineage carries — on soup, multisoup, and both sharded
    twins (on non-Mosaic backends the fused spelling runs the full-width
    masked phase chain, which makes this exact by construction; the
    megakernel itself is parity-tested in interpret mode below, to float
    tolerance like every fused Pallas chain).
  * the megakernel's in-block phases — attack, counterpart post-attack
    recompute, imitation/train chains, respawn — agree with the XLA
    phase composition for every variant (interpret mode).
  * ``population_dtype='bf16'`` keeps integer state exact (int32
    arithmetic, never quantized), agrees bitwise between the fused and
    phase spellings, and stays within the PARITY.md per-generation
    tolerance vs f32.
  * ``population_dtype='int8'`` does the same with quantized codes +
    per-particle scales; fused==phases is bitwise BY CONSTRUCTION here
    (dequant/requant outside the kernel — the quantize-point contract).
  * compact-phase configs are subsumed under 'fused' (masks replace
    compaction), including the capacity-overflow regime where the chain's
    compact path falls back to full width.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import multisoup, soup
from srnn_tpu.init import fresh_lanes, init_population
from srnn_tpu.soup import SoupConfig, evolve, seed
from srnn_tpu.topology import Topology

WW = Topology("weightwise", width=2, depth=2)
AGG = Topology("aggregating", width=2, depth=2)
FFT = Topology("fft", width=2, depth=2)
RNN = Topology("recurrent", width=2, depth=2)


def _full_dynamics(topo, **over):
    kw = dict(topo=topo, size=32, attacking_rate=0.3, learn_from_rate=0.3,
              learn_from_severity=1, train=1, remove_divergent=True,
              remove_zero=True, layout="popmajor")
    kw.update(over)
    return SoupConfig(**kw)


def _leaves_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        if jax.dtypes.issubdtype(getattr(x, "dtype", None),
                                 jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------------ soup bit-identity


@pytest.mark.parametrize("topo", [WW, RNN], ids=lambda t: t.variant)
def test_fused_soup_bitwise_f32(topo):
    """Fused vs phase-chain evolve: state, events-derived carries, and the
    lineage window all bit-identical at f32 (full dynamics)."""
    from srnn_tpu.telemetry.dynamics import seed_lineage

    cfg = _full_dynamics(topo)
    st = seed(cfg, jax.random.key(0))
    lin = seed_lineage(cfg.size)
    ref = evolve(cfg, st, generations=3, metrics=True, health=True,
                 lineage=True, lineage_state=lin, lineage_capacity=256)
    got = evolve(cfg._replace(generation_impl="fused"), st, generations=3,
                 metrics=True, health=True, lineage=True, lineage_state=lin,
                 lineage_capacity=256)
    _leaves_equal(ref, got)


def test_fused_soup_respawn_draws_fused_bitwise():
    """The fused-draw respawn stream rides the fused path unchanged."""
    cfg = _full_dynamics(WW, respawn_draws="fused")
    st = seed(cfg, jax.random.key(1))
    ref = evolve(cfg, st, generations=3)
    got = evolve(cfg._replace(generation_impl="fused"), st, generations=3)
    _leaves_equal(ref, got)


def test_fused_multisoup_bitwise_f32():
    """Mixed population (cross-type attacks stay XLA; per-type blocks take
    the fused route): bit-identical state + per-type metrics carries."""
    mcfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(12, 12), attacking_rate=0.4,
        learn_from_rate=0.3, learn_from_severity=1, train=1,
        remove_divergent=True, remove_zero=True, layout="popmajor")
    st = multisoup.seed_multi(mcfg, jax.random.key(2))
    ref = multisoup.evolve_multi(mcfg, st, generations=3, metrics=True,
                                 health=True)
    got = multisoup.evolve_multi(mcfg._replace(generation_impl="fused"), st,
                                 generations=3, metrics=True, health=True)
    _leaves_equal(ref, got)


def test_fused_sharded_soup_bitwise(mesh=None):
    """Sharded popmajor soup: fused vs phases bitwise on the same mesh;
    vs the single-device fused run to the documented compounded-ulp
    tolerance (shard-width fusion differences), uids exact."""
    from srnn_tpu.parallel import make_sharded_state, soup_mesh
    from srnn_tpu.parallel.sharded_soup import sharded_evolve

    mesh = soup_mesh()
    cfg = _full_dynamics(WW, size=mesh.devices.size * 4)
    st = make_sharded_state(cfg, mesh, jax.random.key(3))
    ref = sharded_evolve(cfg, mesh, st, generations=3, metrics=True)
    got = sharded_evolve(cfg._replace(generation_impl="fused"), mesh, st,
                         generations=3, metrics=True)
    _leaves_equal(ref, got)
    single = evolve(cfg._replace(generation_impl="fused"),
                    seed(cfg, jax.random.key(3)), generations=3)
    np.testing.assert_array_equal(np.asarray(single.uids),
                                  np.asarray(got[0].uids))
    np.testing.assert_allclose(np.asarray(single.weights),
                               np.asarray(got[0].weights),
                               rtol=1e-4, atol=2e-6)


def test_fused_sharded_multisoup_bitwise():
    from srnn_tpu.parallel import soup_mesh
    from srnn_tpu.parallel.sharded_multisoup import (
        make_sharded_multi_state, sharded_evolve_multi)

    mesh = soup_mesh()
    d = mesh.devices.size
    mcfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(2 * d, 2 * d), attacking_rate=0.4,
        learn_from_rate=0.3, learn_from_severity=1, train=1,
        remove_divergent=True, remove_zero=True, layout="popmajor")
    st = make_sharded_multi_state(mcfg, mesh, jax.random.key(4))
    ref = sharded_evolve_multi(mcfg, mesh, st, generations=2, metrics=True)
    got = sharded_evolve_multi(mcfg._replace(generation_impl="fused"), mesh,
                               st, generations=2, metrics=True)
    _leaves_equal(ref, got)


# --------------------------------------------- megakernel interpret parity


@pytest.mark.parametrize("topo", [WW, AGG, FFT, RNN], ids=lambda t: t.variant)
def test_generation_kernel_interpret_matches_phases(topo):
    """The megakernel body (attack -> counterpart recompute -> imitation
    chain -> train chain -> respawn) agrees with the XLA phase composition
    in interpret mode, per variant — including learners whose imitation
    target was attacked this generation (the in-block recompute)."""
    from srnn_tpu.ops.pallas_generation import generation_popmajor
    from srnn_tpu.ops.popmajor import (apply_popmajor, learn_epochs_popmajor,
                                       train_epochs_popmajor)
    from srnn_tpu.ops.predicates import is_diverged, is_zero

    n, sev, train, lr, eps = 40, 1, 2, 0.01, 1e-4
    wT = (init_population(topo, jax.random.key(1), n) * 0.4).T
    # every third lane attacked; learn targets stride over the population,
    # so some imitation targets ARE attacked victims
    att_idx = jnp.where(jnp.arange(n) % 3 == 0, (jnp.arange(n) * 7) % n, -1)
    has_attacker = att_idx >= 0
    learn_gate = (jnp.arange(n) % 4) == 1
    learn_tgt = (jnp.arange(n) * 3) % n
    fresh = fresh_lanes(topo, jax.random.key(2), n)
    assert bool(has_attacker[learn_tgt][learn_gate].any())

    # phase-chain reference
    ref = jnp.where(has_attacker[None, :],
                    apply_popmajor(topo, wT[:, jnp.clip(att_idx, 0)], wT), wT)
    learned, _ = learn_epochs_popmajor(topo, ref, ref[:, learn_tgt], sev, lr,
                                       "sequential")
    ref = jnp.where(learn_gate[None, :], learned, ref)
    ref, ref_loss = train_epochs_popmajor(topo, ref, train, lr, "sequential")
    ref_div = is_diverged(ref, axis=0)
    ref_zero = is_zero(ref, eps, axis=0) & ~ref_div
    ref = jnp.where((ref_div | ref_zero)[None, :], fresh, ref)

    oa = att_idx[learn_tgt]
    out, loss, div, zero = generation_popmajor(
        topo, wT, fresh, wT[:, jnp.clip(att_idx, 0)], has_attacker,
        wT[:, learn_tgt], wT[:, jnp.clip(oa, 0)], oa >= 0, learn_gate,
        severity=sev, train=train, lr=lr, remove_divergent=True,
        remove_zero=True, epsilon=eps, interpret=True)
    np.testing.assert_array_equal(np.asarray(div), np.asarray(ref_div))
    np.testing.assert_array_equal(np.asarray(zero), np.asarray(ref_zero))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref_loss),
                               rtol=1e-4, atol=1e-6)


# ------------------------------------------------------------ bf16 mode


def test_bf16_fused_matches_phases_bitwise():
    """At bf16 the fused and phase spellings still agree BITWISE (same
    rounding points: one downcast per generation)."""
    cfg = _full_dynamics(WW, population_dtype="bf16")
    st = seed(cfg, jax.random.key(5))
    assert st.weights.dtype == jnp.bfloat16
    ref = evolve(cfg, st, generations=3, metrics=True)
    got = evolve(cfg._replace(generation_impl="fused"), st, generations=3,
                 metrics=True)
    _leaves_equal(ref, got)


def test_bf16_integer_state_exact_and_per_gen_tolerance():
    """100 generations of bf16 full dynamics: integer state stays exact
    int32 arithmetic (dtype, monotone uid counter, recountable deaths),
    and ONE generation from a shared state stays within the PARITY.md
    per-generation tolerance (rel L-inf < 2^-7; bound 2^-8 per rounding,
    measured ~3e-3 — benchmarks/parity_sweep.py sweeps this)."""
    cfg16 = _full_dynamics(WW, size=64, train=2,
                           generation_impl="fused",
                           population_dtype="bf16",
                           respawn_draws="fused")
    cfg32 = cfg16._replace(population_dtype="f32")
    st16 = seed(cfg16, jax.random.key(7))
    out = evolve(cfg16, st16, generations=100)
    assert out.weights.dtype == jnp.bfloat16
    assert out.uids.dtype == jnp.int32
    assert int(out.time) == 100
    # uid invariants: every minted uid came from the exact counter stream
    assert int(jnp.max(out.uids)) < int(out.next_uid)
    assert int(out.next_uid) >= cfg16.size

    # per-generation drift vs f32 from the SAME (bf16-cast) start
    worst = 0.0
    st32 = st16._replace(weights=st16.weights.astype(jnp.float32))
    for _ in range(5):
        n32 = evolve(cfg32, st32, generations=1)
        st16 = evolve(cfg16, st16, generations=1)
        np.testing.assert_array_equal(np.asarray(n32.uids),
                                      np.asarray(st16.uids))
        w32 = np.asarray(n32.weights, np.float32)
        w16 = np.asarray(st16.weights, np.float32)
        fin = np.isfinite(w32).all(1) & np.isfinite(w16).all(1)
        scale = max(float(np.abs(w32[fin]).max()), 1e-9)
        worst = max(worst,
                    float(np.abs(w32[fin] - w16[fin]).max()) / scale)
        st32 = st16._replace(weights=st16.weights.astype(jnp.float32))
    assert worst < 2 ** -7, worst


def test_bf16_sequential_mode_rejected():
    cfg = SoupConfig(topo=WW, size=8, mode="sequential",
                     population_dtype="bf16")
    with pytest.raises(ValueError, match="population_dtype"):
        soup.evolve_step(cfg, seed(cfg, jax.random.key(0)))


# ------------------------------------------------------------ int8 mode


def test_int8_fused_matches_phases_bitwise():
    """At int8 the fused and phase spellings agree BITWISE by
    construction: dequant/requant sit OUTSIDE the kernel (the
    quantize-point contract), so both spellings consume the same
    dequantized f32 view — stronger than the bf16 case, where the cast
    point had to be matched inside the kernel."""
    cfg = _full_dynamics(WW, population_dtype="int8")
    st = seed(cfg, jax.random.key(5))
    assert st.weights.dtype == jnp.int8 and st.scales is not None
    ref = evolve(cfg, st, generations=3, metrics=True)
    got = evolve(cfg._replace(generation_impl="fused"), st, generations=3,
                 metrics=True)
    _leaves_equal(ref, got)


def test_int8_fused_multisoup_bitwise():
    """Heterogeneous int8 population: per-type quantized blocks through
    the fused route, bitwise vs phases (scales ride per type)."""
    mcfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(12, 12), attacking_rate=0.4,
        learn_from_rate=0.3, learn_from_severity=1, train=1,
        remove_divergent=True, remove_zero=True, layout="popmajor",
        population_dtype="int8")
    st = multisoup.seed_multi(mcfg, jax.random.key(2))
    assert all(w.dtype == jnp.int8 for w in st.weights)
    ref = multisoup.evolve_multi(mcfg, st, generations=3, metrics=True,
                                 health=True)
    got = multisoup.evolve_multi(mcfg._replace(generation_impl="fused"),
                                 st, generations=3, metrics=True,
                                 health=True)
    _leaves_equal(ref, got)


def test_int8_fused_sharded_twins_bitwise():
    """Both sharded surfaces at int8: fused vs phases bitwise on the
    same mesh (per-shard scales are per-particle, so sharding never
    changes the quantization grid)."""
    from srnn_tpu.parallel import make_sharded_state, soup_mesh
    from srnn_tpu.parallel.sharded_multisoup import (
        make_sharded_multi_state, sharded_evolve_multi)
    from srnn_tpu.parallel.sharded_soup import sharded_evolve

    mesh = soup_mesh()
    d = mesh.devices.size
    cfg = _full_dynamics(WW, size=d * 4, population_dtype="int8")
    st = make_sharded_state(cfg, mesh, jax.random.key(3))
    ref = sharded_evolve(cfg, mesh, st, generations=3, metrics=True)
    got = sharded_evolve(cfg._replace(generation_impl="fused"), mesh, st,
                         generations=3, metrics=True)
    _leaves_equal(ref, got)

    mcfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(2 * d, 2 * d), attacking_rate=0.4,
        learn_from_rate=0.3, learn_from_severity=1, train=1,
        remove_divergent=True, remove_zero=True, layout="popmajor",
        population_dtype="int8")
    mst = make_sharded_multi_state(mcfg, mesh, jax.random.key(4))
    mref = sharded_evolve_multi(mcfg, mesh, mst, generations=2,
                                metrics=True)
    mgot = sharded_evolve_multi(mcfg._replace(generation_impl="fused"),
                                mesh, mst, generations=2, metrics=True)
    _leaves_equal(mref, mgot)


def test_int8_integer_state_exact_and_per_gen_tolerance():
    """100 generations of int8 full dynamics: integer state stays exact
    int32 arithmetic (never quantized), and ONE generation from a shared
    dequantized state stays within the PARITY.md per-generation bound
    (rel L-inf < 2^-7; bound is half a step of the per-particle scale
    amax/127 ~ 2^-8 per generation, measured ~3.9e-3 —
    benchmarks/parity_sweep.py --rows int8 sweeps this)."""
    from srnn_tpu.soup import _upcast

    cfg8 = _full_dynamics(WW, size=64, train=2,
                          generation_impl="fused",
                          population_dtype="int8",
                          respawn_draws="fused")
    cfg32 = cfg8._replace(population_dtype="f32")
    st8 = seed(cfg8, jax.random.key(7))
    out = evolve(cfg8, st8, generations=100)
    assert out.weights.dtype == jnp.int8
    assert out.scales is not None
    assert out.uids.dtype == jnp.int32
    assert int(out.time) == 100
    assert int(jnp.max(out.uids)) < int(out.next_uid)
    assert int(out.next_uid) >= cfg8.size

    def as_f32(st):
        return st._replace(weights=_upcast(cfg8, st.weights, st.scales),
                           scales=None)

    worst = 0.0
    for _ in range(5):
        n32 = evolve(cfg32, as_f32(st8), generations=1)
        st8 = evolve(cfg8, st8, generations=1)
        np.testing.assert_array_equal(np.asarray(n32.uids),
                                      np.asarray(st8.uids))
        w32 = np.asarray(n32.weights, np.float32)
        w8 = np.asarray(as_f32(st8).weights, np.float32)
        fin = np.isfinite(w32).all(1) & np.isfinite(w8).all(1)
        scale = max(float(np.abs(w32[fin]).max()), 1e-9)
        worst = max(worst,
                    float(np.abs(w32[fin] - w8[fin]).max()) / scale)
    assert worst < 2 ** -7, worst


def test_fused_kernel_glue_end_to_end(monkeypatch):
    """Drive the MOSAIC-route dispatch glue (operand gathers, draw
    streams, dead-rank uid minting) — not just the kernel body — by
    forcing the kernel route on and running the kernel in interpret mode.
    Without this the ~300 lines of fused glue are dead code on CPU CI:
    every bitwise test above exercises only the XLA fallback."""
    import functools

    import srnn_tpu.ops.pallas_generation as pg
    from srnn_tpu import soup as soup_mod
    from srnn_tpu.parallel import make_sharded_state, soup_mesh

    real = pg.generation_popmajor
    monkeypatch.setattr(pg, "generation_popmajor",
                        functools.partial(real, interpret=True))
    monkeypatch.setattr(soup_mod, "_fused_kernel_route", lambda cfg: True)
    monkeypatch.setattr(multisoup, "_fused_type_route",
                        lambda cfg, topo: True)

    def check(ref, got):
        np.testing.assert_array_equal(np.asarray(ref[0].uids),
                                      np.asarray(got[0].uids))
        assert int(ref[0].next_uid) == int(got[0].next_uid)
        np.testing.assert_array_equal(np.asarray(ref[1].action),
                                      np.asarray(got[1].action))
        np.testing.assert_array_equal(np.asarray(ref[1].counterpart),
                                      np.asarray(got[1].counterpart))
        r, g = np.asarray(ref[0].weights), np.asarray(got[0].weights)
        fin = np.isfinite(r) & np.isfinite(g)
        np.testing.assert_array_equal(np.isfinite(r), np.isfinite(g))
        np.testing.assert_allclose(g[fin], r[fin], rtol=2e-5, atol=1e-6)

    # sizes unique to THIS test: jit caches on config, and a config traced
    # elsewhere (kernel route off) would silently bypass the monkeypatch
    cfg = _full_dynamics(WW, size=24)
    st = seed(cfg, jax.random.key(11))
    check(soup.evolve_step(cfg, st),
          soup.evolve_step(cfg._replace(generation_impl="fused"), st))

    mesh = soup_mesh()
    shcfg = _full_dynamics(WW, size=mesh.devices.size * 3)
    shst = make_sharded_state(shcfg, mesh, jax.random.key(12))
    from srnn_tpu.parallel.sharded_soup import sharded_evolve_step

    check(sharded_evolve_step(shcfg, mesh, shst),
          sharded_evolve_step(shcfg._replace(generation_impl="fused"),
                              mesh, shst))

    mcfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(10, 14), attacking_rate=0.4,
        learn_from_rate=0.3, learn_from_severity=1, train=1,
        remove_divergent=True, remove_zero=True, layout="popmajor")
    mst = multisoup.seed_multi(mcfg, jax.random.key(13))
    mref = multisoup.evolve_multi_step(mcfg, mst)
    mgot = multisoup.evolve_multi_step(
        mcfg._replace(generation_impl="fused"), mst)
    for t in range(2):
        np.testing.assert_array_equal(np.asarray(mref[0].uids[t]),
                                      np.asarray(mgot[0].uids[t]))
        np.testing.assert_array_equal(np.asarray(mref[1].action[t]),
                                      np.asarray(mgot[1].action[t]))
        r = np.asarray(mref[0].weights[t])
        g = np.asarray(mgot[0].weights[t])
        fin = np.isfinite(r) & np.isfinite(g)
        np.testing.assert_allclose(g[fin], r[fin], rtol=2e-5, atol=1e-6)


# ------------------------------------------------- config fences & compat


def test_fused_rowmajor_rejected():
    cfg = SoupConfig(topo=WW, size=8, layout="rowmajor",
                     generation_impl="fused")
    with pytest.raises(ValueError, match="popmajor"):
        soup.evolve_step(cfg, seed(cfg, jax.random.key(0)))


def test_fused_subsumes_pallas_legs_rejected():
    cfg = _full_dynamics(WW, generation_impl="fused", train_impl="pallas")
    with pytest.raises(ValueError, match="subsumed"):
        soup.evolve_step(cfg, seed(cfg._replace(train_impl="xla"),
                                   jax.random.key(0)))


def test_fused_kernel_fence_rejects_offenvelope():
    """Off-envelope topologies (no output-expressible activation grad)
    reject upfront with a message, mirroring train_impl='pallas'."""
    cfg = _full_dynamics(WW.with_(activation="swish"),
                         generation_impl="fused")
    with pytest.raises(ValueError, match="generation_impl='phases'"):
        soup._check_popmajor(cfg)


def test_fused_subsumes_compact_incl_overflow(monkeypatch):
    """attack_impl='compact' under 'fused' is subsumed by phase masks; in
    the capacity-OVERFLOW regime the chain's compact path falls back to
    full width, so the two agree (uids exact, weights to the documented
    lax.cond FMA-contraction ulps)."""
    from srnn_tpu import soup as soup_mod

    cfg_compact = _full_dynamics(WW, size=64, attacking_rate=0.5,
                                 learn_from_rate=-1.0,
                                 attack_impl="compact")
    st = seed(cfg_compact, jax.random.key(9))
    # force a capacity below the expected attacker count: the compact
    # branch overflows and lax.cond takes the full-width fallback
    monkeypatch.setattr(soup_mod, "_attack_capacity", lambda n, rate: 16)
    ref = evolve(cfg_compact, st, generations=2)
    got = evolve(cfg_compact._replace(generation_impl="fused"), st,
                 generations=2)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(got.uids))
    f, g = np.asarray(ref.weights), np.asarray(got.weights)
    finite = np.isfinite(f).all(axis=1) & np.isfinite(g).all(axis=1)
    np.testing.assert_allclose(g[finite], f[finite], rtol=1e-5, atol=1e-7)


def test_fused_supported_predicates():
    from srnn_tpu.multisoup import MultiSoupConfig, fused_supported_multi
    from srnn_tpu.soup import fused_supported

    assert fused_supported(_full_dynamics(WW))
    assert not fused_supported(_full_dynamics(WW, layout="rowmajor"))
    assert not fused_supported(_full_dynamics(WW, train_impl="pallas"))
    assert not fused_supported(
        _full_dynamics(WW.with_(activation="swish")))
    m = MultiSoupConfig(topos=(WW, AGG), sizes=(8, 8), layout="popmajor")
    assert fused_supported_multi(m)
    assert not fused_supported_multi(m._replace(layout="rowmajor"))
    assert not fused_supported_multi(m._replace(apply_impl="pallas"))
