"""Every paper-experiment entry point runs end-to-end at smoke scale and
writes the reference-style artifacts."""

import os

import numpy as np
import pytest

from srnn_tpu.experiment import load_artifact
from srnn_tpu.fixtures import identity_fixpoint_flat, vary
from srnn_tpu.setups import REGISTRY
from srnn_tpu.topology import Topology

ALL = sorted(REGISTRY)


def test_registry_covers_reference_scripts_plus_mega_soup():
    assert ALL == [
        "applying_fixpoints", "fixpoint_density", "known_fixpoint_variation",
        "learn_from_soup", "mega_multisoup", "mega_soup",
        "mixed_self_fixpoints", "mixed_soup", "network_trajectorys",
        "soup_trajectorys", "training_fixpoints",
    ]  # the nine reference scripts + the two mega-scale entry points


@pytest.mark.parametrize("name", ALL)
def test_setup_smoke(name, tmp_path):
    run_dir = REGISTRY[name](["--smoke", "--root", str(tmp_path), "--seed", "1"])
    assert os.path.isdir(run_dir)
    assert os.path.exists(os.path.join(run_dir, "log.txt"))
    assert os.path.exists(os.path.join(run_dir, "meta.json"))


def test_applying_fixpoints_artifacts(tmp_path):
    run_dir = REGISTRY["applying_fixpoints"](
        ["--smoke", "--root", str(tmp_path), "--record"])
    counters = load_artifact(os.path.join(run_dir, "all_counters"))
    assert counters.shape == (3, 5)
    assert counters.sum() == 3 * 4  # 3 archs x 4 smoke trials
    names = load_artifact(os.path.join(run_dir, "all_names"))
    assert "Weightwise" in names[0]
    traj = load_artifact(os.path.join(run_dir, "trajectorys"))
    assert traj["weightwise"].shape == (11, 4, 14)  # steps+1, trials, P


def test_mixed_soup_sweep_shape(tmp_path):
    run_dir = REGISTRY["mixed_soup"](["--smoke", "--root", str(tmp_path)])
    data = load_artifact(os.path.join(run_dir, "all_data"))
    assert len(data) == 2  # WW + Agg
    assert data[0]["xs"] == [0, 3]
    # rates are avg particles per 10-particle soup, bounded by soup size
    assert all(0.0 <= y <= 6.0 for y in data[0]["ys"] + data[0]["zs"])


def test_soup_trajectorys_artifact(tmp_path):
    run_dir = REGISTRY["soup_trajectorys"](["--smoke", "--root", str(tmp_path)])
    soup = load_artifact(os.path.join(run_dir, "soup"))
    g, n, p = soup["weights"].shape
    assert (g, n, p) == (5, 6, 14)
    assert soup["action"].shape == (5, 6)
    # train=2 > 0 means surviving particles log train_self (code 4) unless dead
    assert set(np.unique(soup["action"])) <= {4, 5, 6}


def test_known_fixpoint_variation_monotonic(tmp_path):
    """Smaller perturbations must survive (weakly) longer as fixpoints —
    the qualitative shape of the reference baseline (BASELINE.md)."""
    run_dir = REGISTRY["known_fixpoint_variation"](
        ["--root", str(tmp_path), "--depth", "4", "--trials", "16",
         "--max-steps", "30"])
    data = load_artifact(os.path.join(run_dir, "data"))
    zs = data["zs"].reshape(4, 16).mean(axis=1)  # per-scale avg time-as-fixpoint
    assert zs[0] <= zs[-1]
    ys = data["ys"].reshape(4, 16).mean(axis=1)
    assert ys[0] <= ys[-1]


def test_vary_bounds_and_identity_fixture():
    import jax

    topo = Topology("weightwise", width=2, depth=2)
    flat = identity_fixpoint_flat(topo)
    # bit-for-bit the reference fixture (known-fixpoint-variation.py:20-25)
    expected = np.concatenate([
        np.array([[1, 0], [0, 0], [0, 0], [0, 0]], np.float32).reshape(-1),
        np.array([[1, 0], [0, 0]], np.float32).reshape(-1),
        np.array([[1], [0]], np.float32).reshape(-1)])
    np.testing.assert_array_equal(np.asarray(flat), expected)
    perturbed = vary(jax.random.key(0), flat, e=0.5)
    delta = np.abs(np.asarray(perturbed) - expected)
    assert (delta <= 0.5).all() and (delta > 0).all()


def test_mega_soup_smoke_and_bit_exact_resume(tmp_path):
    """mega_soup checkpoints every chunk; an interrupted run resumed from the
    last checkpoint finishes IDENTICAL to an uninterrupted one (same PRNG
    stream through the orbax round trip)."""
    from srnn_tpu.experiment import restore_checkpoint

    # uninterrupted: 6 generations
    d_full = REGISTRY["mega_soup"](["--smoke", "--root", str(tmp_path / "full")])
    # interrupted twin: same seed, stop at gen 4, then resume to 6
    d_half = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path / "half"), "--generations", "4"])
    # the conflicting --attacking-rate must LOSE to the run's saved config —
    # the bit-exactness assertions below prove the original dynamics won
    d_resumed = REGISTRY["mega_soup"](
        ["--smoke", "--resume", d_half, "--attacking-rate", "0.9"])
    assert d_resumed == d_half

    want = restore_checkpoint(os.path.join(d_full, "ckpt-gen00000006"))
    got = restore_checkpoint(os.path.join(d_half, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(want.weights), np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(want.uids), np.asarray(got.uids))
    assert int(got.time) == 6
    # the resumed run appended to the original log
    log = open(os.path.join(d_half, "log.txt")).read()
    assert "resumed from ckpt-gen00000004" in log and "done:" in log


def test_mega_soup_popmajor_sequential_train_runs(tmp_path):
    """popmajor + batch-1 sequential training used to be a hard-errored
    compile pathology; the flattened epochs*samples scan
    (ops/popmajor.py::_ww_seq_sgd_flat) makes it a supported config."""
    d = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path), "--train", "2",
         "--train-mode", "sequential", "--layout", "popmajor"])
    assert "done:" in open(os.path.join(d, "log.txt")).read()


def test_mega_soup_capture_survives_resume(tmp_path):
    """Interrupt a capturing run, resume it (WITHOUT re-passing
    --capture-every): capture continues per the saved config, the store is
    appended to not truncated, and every pre- and post-resume frame reads
    back (the round-2 TrajStore data-loss bug)."""
    from srnn_tpu.utils import read_store

    d_half = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path), "--generations", "4",
         "--capture-every", "2"])
    traj = os.path.join(d_half, "soup.traj")
    pre = read_store(traj)
    assert pre["generations"].tolist() == [2, 4]
    d_resumed = REGISTRY["mega_soup"](["--smoke", "--resume", d_half])
    assert d_resumed == d_half
    out = read_store(traj)
    assert out["generations"].tolist() == [2, 4, 6]
    np.testing.assert_array_equal(out["weights"][:2], pre["weights"])
    log = open(os.path.join(d_half, "log.txt")).read()
    assert "appending after 2 existing frames" in log


def test_mega_soup_bad_capture_cadence_leaves_no_run_dir(tmp_path):
    """Validation happens BEFORE the Experiment is entered: a rejected
    invocation must not leave a run dir without meta.json."""
    with pytest.raises(SystemExit):
        REGISTRY["mega_soup"](
            ["--smoke", "--root", str(tmp_path), "--capture-every", "3",
             "--checkpoint-every", "4"])
    assert not os.path.exists(tmp_path) or os.listdir(str(tmp_path)) == []


def test_experiment_wall_seconds_cumulative(tmp_path):
    """meta.json wall_seconds accumulates across attach() sessions instead
    of being overwritten by the last session's runtime."""
    import json
    import time as _t

    from srnn_tpu.experiment import Experiment

    with Experiment("wall", root=str(tmp_path)) as exp:
        _t.sleep(0.05)
    meta_path = os.path.join(exp.dir, "meta.json")
    first = json.load(open(meta_path))["wall_seconds"]
    assert first > 0
    exp2 = Experiment.attach(exp.dir)
    _t.sleep(0.05)
    exp2.__exit__(None, None, None)
    second = json.load(open(meta_path))["wall_seconds"]
    assert second >= first + 0.05


def test_mega_soup_sharded_capture_and_resume(tmp_path):
    """--sharded runs the soup over the 8-device mesh with capture; an
    interrupted sharded run resumes bit-exactly (saved config keeps
    sharded=True) and the store appends rather than truncates."""
    from srnn_tpu.experiment import restore_checkpoint
    from srnn_tpu.utils import read_sharded_store

    d_full = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path / "full"), "--sharded",
         "--capture-every", "2"])
    d_half = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path / "half"), "--sharded",
         "--capture-every", "2", "--generations", "4"])
    d_resumed = REGISTRY["mega_soup"](["--smoke", "--resume", d_half])
    assert d_resumed == d_half

    want = restore_checkpoint(os.path.join(d_full, "ckpt-gen00000006"))
    got = restore_checkpoint(os.path.join(d_half, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(want.weights),
                                  np.asarray(got.weights))
    out = read_sharded_store(os.path.join(d_half, "soup.traj"))
    assert out["generations"].tolist() == [2, 4, 6]
    np.testing.assert_array_equal(out["weights"][-1], np.asarray(got.weights))


@pytest.mark.slow
def test_mega_multisoup_bit_exact_resume_and_sharded(tmp_path):
    """The heterogeneous mega-soup entry point checkpoints MultiSoupState
    and resumes bit-exactly; the sharded path produces a valid run too."""
    from srnn_tpu.experiment import restore_multi_checkpoint

    d_full = REGISTRY["mega_multisoup"](
        ["--smoke", "--root", str(tmp_path / "full")])
    d_half = REGISTRY["mega_multisoup"](
        ["--smoke", "--root", str(tmp_path / "half"), "--generations", "4"])
    d_resumed = REGISTRY["mega_multisoup"](
        ["--smoke", "--resume", d_half, "--attacking-rate", "0.9"])
    assert d_resumed == d_half

    want = restore_multi_checkpoint(os.path.join(d_full, "ckpt-gen00000006"))
    got = restore_multi_checkpoint(os.path.join(d_half, "ckpt-gen00000006"))
    for t in range(3):
        np.testing.assert_array_equal(np.asarray(want.weights[t]),
                                      np.asarray(got.weights[t]))
        np.testing.assert_array_equal(np.asarray(want.uids[t]),
                                      np.asarray(got.uids[t]))
    assert int(got.time) == 6
    log = open(os.path.join(d_half, "log.txt")).read()
    assert "resumed from ckpt-gen00000004" in log and "done:" in log

    d_sh = REGISTRY["mega_multisoup"](
        ["--smoke", "--root", str(tmp_path / "sh"), "--sharded"])
    assert "done:" in open(os.path.join(d_sh, "log.txt")).read()


def test_mega_multisoup_per_type_capture_survives_resume(tmp_path):
    """Per-type .traj stores capture the heterogeneous soup and append
    across a resume (homogeneous mega_soup capture semantics, per type).

    The capturing runs execute as REAL CLI subprocesses: end-to-end through
    ``python -m srnn_tpu.setups``, and isolated from the suite process —
    the in-process capture flow left the XLA CPU client in a state that
    segfaulted a later unrelated compile (reproducible only across the
    full suite; root cause upstream, isolation is the durable fix)."""
    import subprocess
    import sys

    from srnn_tpu.utils import read_store

    def cli(*argv):
        env = dict(os.environ)
        env["SRNN_SETUPS_PLATFORM"] = "cpu"  # never dial the tunnel
        proc = subprocess.run(
            [sys.executable, "-m", "srnn_tpu.setups", "mega_multisoup",
             *argv], stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=300, env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        out = proc.stdout.decode()
        assert proc.returncode == 0, out
        return out.strip().splitlines()[-1]  # run dir printed last

    d = cli("--smoke", "--root", str(tmp_path), "--generations", "4",
            "--capture-every", "2")
    pre = read_store(os.path.join(d, "soup.t0.traj"))
    assert pre["generations"].tolist() == [2, 4]
    d_resumed = cli("--smoke", "--resume", d)
    assert d_resumed == d
    for t, n_t in enumerate((16, 16, 16)):  # smoke split of 48
        out = read_store(os.path.join(d, f"soup.t{t}.traj"))
        assert out["generations"].tolist() == [2, 4, 6]
        assert out["weights"].shape[1] == n_t
    np.testing.assert_array_equal(
        read_store(os.path.join(d, "soup.t0.traj"))["weights"][:2],
        pre["weights"])
