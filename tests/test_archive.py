"""The run archive & cross-run observatory (telemetry.archive): the
longitudinal index over a results root.

The load-bearing contracts drilled here:

  * ingest is READ-ONLY over run dirs — archiving a LIVE run leaves it
    byte-for-byte identical (the observatory can never perturb science);
  * re-ingest is a watermark no-op — an unchanged root writes NOTHING to
    the store (byte-identical store files), with the one documented
    exception: a previously-``running`` run re-folds because its outcome
    can decay to ``wedged`` by clock alone;
  * the outcome ladder maps exit evidence (meta.json error reprs,
    restart/preempt rows, trail staleness) onto the supervisor's exit
    vocabulary (resilience/supervisor.py): 0/3/69/71/75/137;
  * the no-data contract (exit 2 + explicit flag) holds for
    ``report --runs`` and ``report --compare`` — an empty root never
    renders an empty-but-valid table a controller would trust.

Everything runs on hand-built run-dir fixtures with pinned mtimes and a
pinned clock — no jax, no real runs.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

from srnn_tpu.telemetry import archive, report, watch

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: pinned "wall clock" every ingest in this file runs against (ingest
#: takes ``now=`` exactly so outcomes are deterministic under test)
NOW = 1_700_000_000.0


# ---------------------------------------------------------------------------
# fixtures: hand-built run dirs
# ---------------------------------------------------------------------------


def _write_jsonl(path, rows, torn_tail=None):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        if torn_tail is not None:
            f.write(torn_tail)  # no newline: a clipped in-flight write


def make_run(root, name, *, seed=0, config=None, error="__absent__",
             gps=(100.0, 100.0, 100.0), restarts=0, preempts=0,
             nan_peak=None, census=None, wall=5.0, age=30.0, flops=0.0,
             alerts=(), torn_tail=None):
    """One fake run dir.  ``error="__absent__"`` = no meta.json at all
    (a SIGKILLed or still-running experiment); ``error=None`` = the
    clean-unwind meta ``Experiment.__exit__`` writes; a string = the
    fault's repr.  ``age`` pins every file's mtime to ``NOW - age``."""
    run_dir = os.path.join(root, name)
    os.makedirs(run_dir)
    cfg = {"n": 2048, "generations": 100, "seed": seed}
    cfg.update(config or {})
    with open(os.path.join(run_dir, "config.json"), "w") as f:
        json.dump(cfg, f)
    t0 = NOW - age - 120.0
    rows = []
    for i, g in enumerate(gps):
        rows.append({"kind": "heartbeat", "t": t0 + i,
                     "generation": (i + 1) * 10, "total_generations": 100,
                     "gens_per_sec": g})
    for i in range(restarts):
        rows.append({"kind": "restart", "t": t0 + 50 + i,
                     "restarts": i + 1, "fault": "STALL",
                     "reramped": True})
    for i in range(preempts):
        rows.append({"kind": "preempt", "t": t0 + 60 + i,
                     "generation": 40})
    for rule, state in alerts:
        rows.append({"kind": "alert", "rule": rule, "state": state,
                     "t": t0 + 70})
    if flops:
        rows.append({"kind": "cost", "t": t0 + 5, "entry": "chunk",
                     "flops": flops})
    if nan_peak is not None:
        rows.append({"kind": "metrics", "t": t0 + 80,
                     "metrics": {"soup_health_nan_frac": nan_peak}})
    _write_jsonl(os.path.join(run_dir, "events.jsonl"), rows,
                 torn_tail=torn_tail)
    if census is not None:
        _write_jsonl(os.path.join(run_dir, "lineage.jsonl"),
                     [{"gen_end": 100,
                       "fixpoints": {"census": census, "transitions": {}}}])
    if error != "__absent__":
        with open(os.path.join(run_dir, "meta.json"), "w") as f:
            json.dump({"name": name, "id": "t", "iteration": 0,
                       "seed": seed, "wall_seconds": wall,
                       "error": error}, f)
    ts = NOW - age
    for fn in os.listdir(run_dir):
        p = os.path.join(run_dir, fn)
        if os.path.isfile(p):
            os.utime(p, (ts, ts))
    return run_dir


def _store_bytes(store):
    """{filename: bytes} of every store file — the byte-identity probe."""
    out = {}
    for fn in sorted(os.listdir(store)):
        with open(os.path.join(store, fn), "rb") as f:
            out[fn] = f.read()
    return out


def _tree_state(top):
    """{relpath: (bytes, size, mtime_ns)} over a whole dir tree."""
    out = {}
    for dirpath, _dirs, files in os.walk(top):
        for fn in files:
            p = os.path.join(dirpath, fn)
            st = os.stat(p)
            with open(p, "rb") as f:
                out[os.path.relpath(p, top)] = (f.read(), st.st_size,
                                                st.st_mtime_ns)
    return out


# ---------------------------------------------------------------------------
# outcome classification
# ---------------------------------------------------------------------------


def test_classify_outcome_ladder_unit():
    """The docstring ladder, row by row (first match wins)."""
    c = archive.classify_outcome
    assert c(None, 0, 0, age_s=10.0) == "running"
    assert c(None, 0, 0, age_s=4000.0) == "wedged"
    assert c(None, 0, 0, age_s=None) == "wedged"
    assert c({"error": None}, 0, 1, age_s=1.0) == "preempted"
    assert c({"error": None}, 2, 0, age_s=1.0) == "recovered"
    assert c({"error": None}, 0, 0, age_s=1.0) == "clean"
    assert c({"error": "Preempted('slice going away')"}, 3, 0,
             age_s=1.0) == "preempted"
    assert c({"error": "HostLost('worker 2')"}, 0, 0,
             age_s=1.0) == "host-lost"
    assert c({"error": "CoordinatorTimeout('barrier')"}, 1, 0,
             age_s=1.0) == "host-lost"
    assert c({"error": "StallError('no heartbeat')"}, 3, 0,
             age_s=1.0) == "retries-exhausted"
    assert c({"error": "ValueError('boom')"}, 0, 0, age_s=1.0) == "failed"


def test_outcomes_and_exit_codes_over_run_dirs(tmp_path):
    """End-to-end over hand-built dirs: every exit kind the supervisor
    can produce lands on its documented outcome + exit code."""
    root = str(tmp_path)
    make_run(root, "r-clean", error=None)
    make_run(root, "r-recovered", error=None, restarts=2)
    make_run(root, "r-preempt-clean", error=None, preempts=1)
    make_run(root, "r-preempt-fault",
             error="Preempted('maintenance event')")
    make_run(root, "r-hostlost", error="HostLost('worker 1 gone')")
    make_run(root, "r-retries", error="StallError('wedged chunk')",
             restarts=3)
    make_run(root, "r-failed", error="ValueError('boom')")
    make_run(root, "r-wedged", error="__absent__", age=4000.0)
    make_run(root, "r-running", error="__absent__", age=30.0)

    res = archive.ingest(root, now=NOW)
    assert res["scanned"] == 9 and len(res["ingested"]) == 9
    index = archive.load_index(res["store"])
    got = {k: (r["outcome"], r["exit_code"])
           for k, r in index["runs"].items()}
    assert got == {
        "r-clean": ("clean", 0),
        "r-recovered": ("recovered", 3),
        "r-preempt-clean": ("preempted", 75),
        "r-preempt-fault": ("preempted", 75),
        "r-hostlost": ("host-lost", 71),
        "r-retries": ("retries-exhausted", 69),
        "r-failed": ("failed", 1),
        "r-wedged": ("wedged", 137),
        "r-running": ("running", None),
    }
    # restart evidence folds as the max restart counter, not row count
    assert index["runs"]["r-retries"]["restarts"] == 3


def test_running_decays_to_wedged_by_clock_alone(tmp_path):
    """The one watermark exception: a ``running`` run re-folds on an
    unchanged watermark, because staleness is a clock fact, not a byte
    fact."""
    root = str(tmp_path)
    make_run(root, "r-live", error="__absent__", age=30.0)
    res1 = archive.ingest(root, now=NOW)
    index = archive.load_index(res1["store"])
    assert index["runs"]["r-live"]["outcome"] == "running"
    # nothing on disk changes; only the clock moves past stale_s
    res2 = archive.ingest(root, now=NOW + 1000.0)
    index = archive.load_index(res2["store"])
    assert index["runs"]["r-live"]["outcome"] == "wedged"
    assert res2["ingested"] == ["r-live"]


# ---------------------------------------------------------------------------
# watermark / torn tail / live-run purity
# ---------------------------------------------------------------------------


def test_reingest_is_watermark_noop(tmp_path):
    """Second pass over an unchanged root: zero rows appended, zero
    bytes changed anywhere in the store."""
    root = str(tmp_path)
    make_run(root, "r-a", error=None, seed=0)
    make_run(root, "r-b", error=None, seed=1)
    res1 = archive.ingest(root, now=NOW)
    assert len(res1["ingested"]) == 2 and res1["wrote"]
    before = _store_bytes(res1["store"])
    res2 = archive.ingest(root, now=NOW + 60.0)
    assert res2["ingested"] == [] and res2["unchanged"] == 2
    assert not res2["wrote"]
    assert _store_bytes(res2["store"]) == before


def test_new_bytes_reingest_only_the_changed_run(tmp_path):
    """Incremental: appending to ONE run's events re-folds that run
    only; the sibling stays a stat-call no-op."""
    root = str(tmp_path)
    make_run(root, "r-a", error=None, seed=0)
    b = make_run(root, "r-b", error=None, seed=1)
    archive.ingest(root, now=NOW)
    with open(os.path.join(b, "events.jsonl"), "a") as f:
        f.write(json.dumps({"kind": "heartbeat", "t": NOW,
                            "generation": 99, "gens_per_sec": 50.0})
                + "\n")
    res = archive.ingest(root, now=NOW + 10.0)
    assert res["ingested"] == ["r-b"] and res["unchanged"] == 1


def test_torn_tail_counts_skipped_never_fatal(tmp_path):
    """A clipped in-flight line (killed writer) costs skip counts, not
    the fold: the repo-wide skip-unparseable jsonl contract."""
    root = str(tmp_path)
    make_run(root, "r-torn", error=None,
             torn_tail='{"kind": "heartbeat", "t": 12')
    res = archive.ingest(root, now=NOW)
    row = archive.load_index(res["store"])["runs"]["r-torn"]
    assert row["outcome"] == "clean"
    assert row["skipped_lines"] >= 1
    assert row["gens_per_sec"]["p50"] == 100.0  # intact rows still fold


def test_live_run_ingest_is_byte_identical(tmp_path):
    """THE purity contract: ingesting a live (meta-less, fresh) run
    leaves every byte, size and mtime under the run dir untouched, and
    the store lands outside it."""
    root = str(tmp_path)
    run_dir = make_run(root, "r-live", error="__absent__", age=5.0,
                       nan_peak=0.01, census={"fix_a": 7})
    before = _tree_state(run_dir)
    res = archive.ingest(root, now=NOW)
    assert _tree_state(run_dir) == before
    assert not os.path.abspath(res["store"]).startswith(
        os.path.abspath(run_dir) + os.sep)
    row = archive.load_index(res["store"])["runs"]["r-live"]
    assert row["outcome"] == "running"


# ---------------------------------------------------------------------------
# campaigns / rollups / compare
# ---------------------------------------------------------------------------


def test_campaign_fingerprint_groups_seeds_not_knobs(tmp_path):
    """A seed sweep is ONE campaign (volatile keys excluded from the
    fingerprint); a substantive knob change starts another."""
    root = str(tmp_path)
    make_run(root, "sweep-s0", error=None, seed=0)
    make_run(root, "sweep-s1", error=None, seed=1)
    make_run(root, "big-n", error=None, seed=0, config={"n": 4096})
    doc = archive.runs_doc(root, now=NOW)
    camps = {c["fingerprint"]: c for c in doc["campaigns"]}
    assert len(camps) == 2
    sweep = next(c for c in camps.values() if c["runs"] == 2)
    assert sweep["seeds"] == [0, 1]
    assert sweep["outcomes"] == {"clean": 2}
    assert sweep["gens_per_sec_p50_median"] == 100.0
    by_run = {r["run"]: r for r in doc["runs"]}
    assert by_run["sweep-s0"]["config_fingerprint"] == \
        by_run["sweep-s1"]["config_fingerprint"]
    assert by_run["big-n"]["config_fingerprint"] != \
        by_run["sweep-s0"]["config_fingerprint"]


def test_compare_runs_deltas_against_fixtures(tmp_path):
    root = str(tmp_path)
    a = make_run(root, "r-a", error=None, seed=0, wall=5.0,
                 gps=(100.0, 100.0), census={"fix_a": 10, "fix_b": 2})
    b = make_run(root, "r-b", error=None, seed=1, wall=10.0,
                 gps=(50.0, 50.0), config={"n": 4096},
                 census={"fix_a": 4})
    doc = archive.compare_runs(a, b, now=NOW)
    assert doc["config_diff"]["changed"]["n"] == [2048, 4096]
    assert doc["config_diff"]["same_campaign"] is False
    w = doc["deltas"]["wall_seconds"]
    assert (w["a"], w["b"], w["delta"], w["ratio"]) == (5.0, 10.0, 5.0,
                                                        2.0)
    p50 = doc["deltas"]["gens_per_sec.p50"]
    assert (p50["a"], p50["b"]) == (100.0, 50.0)
    assert doc["census"]["fix_a"] == {"a": 10, "b": 4, "delta": -6}
    assert doc["census"]["fix_b"]["delta"] == -2
    # either side not a run dir -> None (the no-data contract's source)
    empty = os.path.join(root, "not-a-run")
    os.makedirs(empty)
    assert archive.compare_runs(a, empty, now=NOW) is None


# ---------------------------------------------------------------------------
# drift: campaign medians + the persisted latch
# ---------------------------------------------------------------------------


def test_drift_alert_fires_once_then_clears_once(tmp_path):
    """A degraded newest arm breaches the rate leg, latches the
    ``archive_drift`` alert (ONE firing row), stays latched across a
    no-op re-ingest, and clears (ONE cleared row) when the run is
    repaired."""
    root = str(tmp_path)
    make_run(root, "c-r1", error=None, seed=0)
    make_run(root, "c-r2", error=None, seed=1)
    r3 = make_run(root, "c-r3", error=None, seed=2,
                  gps=(10.0, 10.0, 10.0))  # 10 vs median 100: breach
    res = archive.ingest(root, now=NOW)
    legs = {f["leg"] for f in res["drift"]["findings"]}
    assert "gens_per_sec_p50" in legs
    assert [t["state"] for t in res["alert_transitions"]] == ["firing"]
    index = archive.load_index(res["store"])
    assert index["drift_alert"]["state"] == "firing"

    # latched: a second pass emits no duplicate firing edge
    res2 = archive.ingest(root, now=NOW + 10.0)
    assert res2["alert_transitions"] == []

    # repair the degraded arm -> its watermark moves -> re-fold -> clear
    rows = [{"kind": "heartbeat", "t": NOW + i, "generation": (i + 1) * 10,
             "total_generations": 100, "gens_per_sec": 100.0}
            for i in range(3)]
    _write_jsonl(os.path.join(r3, "events.jsonl"), rows)
    os.utime(os.path.join(r3, "events.jsonl"), (NOW + 20, NOW + 20))
    res3 = archive.ingest(root, now=NOW + 30.0)
    assert [t["state"] for t in res3["alert_transitions"]] == ["cleared"]
    assert res3["drift"]["findings"] == []

    # exactly one edge row each in the append-only trail
    with open(os.path.join(res["store"], archive.ARCHIVE_NAME)) as f:
        alert_rows = [json.loads(l) for l in f
                      if '"kind": "alert"' in l]
    assert [r["state"] for r in alert_rows] == ["firing", "cleared"]
    assert all(r["rule"] == "archive_drift" for r in alert_rows)


def test_drift_needs_minimum_history(tmp_path):
    """One predecessor is not a median (MIN_DRIFT_HISTORY guard — the
    regress.py MIN_ROUNDS reasoning): no finding, no latch."""
    root = str(tmp_path)
    make_run(root, "c-r1", error=None, seed=0)
    make_run(root, "c-r2", error=None, seed=1, gps=(10.0, 10.0))
    res = archive.ingest(root, now=NOW)
    assert res["drift"]["findings"] == []
    assert res["alert_transitions"] == []
    camp = next(iter(res["drift"]["campaigns"].values()))
    assert "insufficient history" in \
        camp["legs"]["gens_per_sec_p50"]["verdict"]


# ---------------------------------------------------------------------------
# gc
# ---------------------------------------------------------------------------


def test_gc_keep_bound_compacts_store_never_run_dirs(tmp_path):
    root = str(tmp_path)
    dirs = [make_run(root, f"r-{i}", error=None, seed=i)
            for i in range(4)]
    res = archive.ingest(root, now=NOW)
    before = {d: _tree_state(d) for d in dirs}
    out = archive.gc(root, keep=2, now=NOW + 100.0)
    assert out["kept"] == 2 and out["pruned"] == ["r-0", "r-1"]
    index = archive.load_index(res["store"])
    assert sorted(index["runs"]) == ["r-2", "r-3"]
    with open(os.path.join(res["store"], archive.ARCHIVE_NAME)) as f:
        rows = [json.loads(l) for l in f]
    assert sorted(r["run"] for r in rows if r["kind"] == "run") == \
        ["r-2", "r-3"]
    # retention is a STORE policy: the experiments themselves survive
    assert {d: _tree_state(d) for d in dirs} == before


def test_gc_max_age_days(tmp_path):
    root = str(tmp_path)
    make_run(root, "r-old", error=None)
    res = archive.ingest(root, now=NOW)
    out = archive.gc(root, max_age_days=0.5, now=NOW + 86400.0)
    assert out["pruned"] == ["r-old"] and out["kept"] == 0
    assert archive.load_index(res["store"])["runs"] == {}


# ---------------------------------------------------------------------------
# CLI contracts: report --runs / --compare, watch --archive, archive main
# ---------------------------------------------------------------------------


def test_report_runs_json_contract(tmp_path, capsys):
    root = str(tmp_path)
    make_run(root, "r-clean", error=None)
    make_run(root, "r-failed", error="ValueError('boom')", seed=1)
    rc = report.main([root, "--runs", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["no_data"] is False
    assert {r["run"]: r["outcome"] for r in doc["runs"]} == \
        {"r-clean": "clean", "r-failed": "failed"}
    assert doc["campaigns"] and doc["ingest"]["scanned"] == 2
    # text mode renders the table (outcomes + campaign line)
    rc = report.main([root, "--runs"])
    out = capsys.readouterr().out
    assert rc == 0 and "r-failed" in out and "campaign" in out


def test_report_runs_no_data_contract(tmp_path, capsys):
    """Empty root: exit 2 + explicit ``no_data`` — never an
    empty-but-valid table."""
    root = str(tmp_path)
    rc = report.main([root, "--runs", "--json"])
    cap = capsys.readouterr()
    assert rc == 2
    assert json.loads(cap.out)["no_data"] is True
    rc = report.main([root, "--runs"])
    cap = capsys.readouterr()
    assert rc == 2 and "no data yet" in cap.err


def test_report_compare_cli(tmp_path, capsys):
    root = str(tmp_path)
    a = make_run(root, "r-a", error=None, wall=5.0)
    b = make_run(root, "r-b", error=None, wall=10.0, seed=1)
    rc = report.main([b, "--compare", a, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["a"]["name"] == "r-a" and doc["b"]["name"] == "r-b"
    assert doc["config_diff"]["same_campaign"] is True
    rc = report.main([b, "--compare", a])
    assert rc == 0 and "wall_seconds" in capsys.readouterr().out
    # one side not a run dir -> the no-data contract
    empty = os.path.join(root, "empty")
    os.makedirs(empty)
    rc = report.main([empty, "--compare", a, "--json"])
    cap = capsys.readouterr()
    assert rc == 2 and json.loads(cap.out)["no_data"] is True


def test_watch_archive_once(tmp_path, capsys):
    root = str(tmp_path)
    make_run(root, "r-clean", error=None)
    rc = watch.main([root, "--archive", "--once"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert [r["run"] for r in doc["archive"]["runs"]] == ["r-clean"]


def test_archive_cli_ingest_and_gc(tmp_path, capsys):
    root = str(tmp_path)
    make_run(root, "r-clean", error=None)
    assert archive.main(["ingest", root, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ingested"] == ["r-clean"]
    # second pass: still exit 0, explicit zero ingested
    assert archive.main(["ingest", root, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["ingested"] == []
    assert archive.main(["gc", root, "--keep", "0", "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["pruned"] == ["r-clean"]
    # gc without a bound is a usage error
    assert archive.main(["gc", root]) == 2
    capsys.readouterr()


def test_archive_cli_empty_root_exit_2(tmp_path, capsys):
    assert archive.main(["ingest", str(tmp_path)]) == 2
    assert "no run dirs" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the soup_archive_* exposition
# ---------------------------------------------------------------------------


def test_store_prom_carries_canonical_archive_metrics(tmp_path):
    from srnn_tpu.telemetry.names import CANONICAL_METRICS

    root = str(tmp_path)
    make_run(root, "c-r1", error=None, seed=0)
    make_run(root, "c-r2", error=None, seed=1)
    make_run(root, "c-r3", error=None, seed=2)
    res = archive.ingest(root, now=NOW)
    with open(os.path.join(res["store"], archive.PROM_NAME)) as f:
        text = f.read()
    gauges = watch.parse_prometheus(text)
    assert gauges["srnn_soup_archive_runs"] == 3.0
    assert gauges["srnn_soup_archive_runs_ingested_total"] == 3.0
    assert gauges["srnn_soup_archive_drift_legs"] == 0.0
    # drift ratio gauges carry leg+campaign labels, canonically named
    assert any(k.startswith("srnn_soup_archive_drift_ratio{")
               for k in gauges)
    for name in ("soup_archive_runs", "soup_archive_runs_ingested_total",
                 "soup_archive_drift_ratio", "soup_archive_drift_legs"):
        assert name in CANONICAL_METRICS


# ---------------------------------------------------------------------------
# the bench sidecar: bench.py append hook + regress --from-archive
# ---------------------------------------------------------------------------


def _load_bench_module(tmp_path):
    """Import a COPY of bench.py from tmp so its sidecar (written next
    to ``__file__``) lands in the sandbox, not the repo root."""
    path = os.path.join(str(tmp_path), "bench.py")
    shutil.copy(os.path.join(REPO_ROOT, "bench.py"), path)
    spec = importlib.util.spec_from_file_location("bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_archive_sentinel_appends_and_bounds(tmp_path, monkeypatch):
    monkeypatch.delenv("SRNN_BENCH_ARCHIVE", raising=False)
    bench = _load_bench_module(tmp_path)
    sidecar = os.path.join(str(tmp_path), bench.BENCH_ARCHIVE_NAME)
    result = {"value": 1.0}
    bench._archive_sentinel(result)
    att = result["stage_log"][-1]
    assert att["stage"] == "archive" and att["outcome"] == "ok"
    assert att["rounds"] == 1
    rows = [json.loads(l) for l in open(sidecar)]
    assert rows[0]["kind"] == "bench_round"
    assert rows[0]["result"]["value"] == 1.0
    # bounded: the cap compacts to the newest rounds
    for i in range(bench.BENCH_ARCHIVE_MAX_ROUNDS + 5):
        bench._archive_sentinel({"value": float(i)})
    rows = [json.loads(l) for l in open(sidecar)]
    assert len(rows) == bench.BENCH_ARCHIVE_MAX_ROUNDS
    assert rows[-1]["result"]["value"] == \
        float(bench.BENCH_ARCHIVE_MAX_ROUNDS + 4)


def test_bench_archive_sentinel_opt_out(tmp_path, monkeypatch):
    monkeypatch.setenv("SRNN_BENCH_ARCHIVE", "0")
    bench = _load_bench_module(tmp_path)
    result = {"value": 1.0}
    bench._archive_sentinel(result)
    assert result["stage_log"][-1]["outcome"] == "disabled"
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           bench.BENCH_ARCHIVE_NAME))


def test_regress_from_archive_feeds_history_median(tmp_path):
    """Archived rounds join the committed-glob history: a fresh value
    that regresses against the lone committed file alone is OK once the
    archive's rounds move the median back."""
    regress = os.path.join(REPO_ROOT, "benchmarks", "regress.py")
    root = str(tmp_path)
    committed = os.path.join(root, "BENCH_r01.json")
    with open(committed, "w") as f:
        json.dump({"backend": "cpu", "value": 200.0}, f)
    sidecar = os.path.join(root, "BENCH_archive.jsonl")
    _write_jsonl(sidecar,
                 [{"kind": "bench_round", "t": 1.0,
                   "result": {"backend": "cpu", "value": 100.0}},
                  {"kind": "bench_round", "t": 2.0,
                   "result": {"backend": "cpu", "value": 100.0}}])
    fresh = os.path.join(root, "fresh.json")
    with open(fresh, "w") as f:
        json.dump({"backend": "cpu", "value": 100.0}, f)

    def run(extra):
        proc = subprocess.run(
            [sys.executable, regress, fresh,
             "--history", os.path.join(root, "BENCH_r*.json"), "--json"]
            + extra,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, timeout=60)
        return proc.returncode, json.loads(proc.stdout.decode())

    # committed history alone: 100 vs median 200 = -50% -> regression
    rc, doc = run([])
    assert rc == 1
    assert any(f["leg"] == "apps_per_chip" for f in doc["regressions"])
    # + archive rounds: median([200, 100, 100]) = 100 -> ok, and the
    # archive labels show up in the judged history
    rc, doc = run(["--from-archive", sidecar])
    assert rc == 0
    leg = next(l for l in doc["legs"] if l["leg"] == "apps_per_chip")
    assert leg["verdict"] == "ok"
    assert "archive[0]" in leg["history_rounds"]
