"""Distributed tests on the 8-device virtual CPU mesh (conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8; SURVEY §4 implication (c))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology, init_population
from srnn_tpu.nets import apply_to_weights
from srnn_tpu.parallel import (
    ring_rnn_apply,
    shard_population,
    sharded_count,
    sharded_evolve,
    sharded_evolve_step,
    soup_mesh,
)
from srnn_tpu.parallel import make_sharded_state
from srnn_tpu.soup import SoupConfig, count, evolve_step, seed
from tests.test_apply import WW


def test_sharded_attack_train_bitwise_matches_unsharded(mesh):
    """Attack + train phases are bit-identical to the single-device parallel
    soup under matched keys (no respawn, no learn_from)."""
    cfg = SoupConfig(topo=WW, size=16, attacking_rate=0.4, learn_from_rate=0.0,
                     train=2)
    s0 = seed(cfg, jax.random.key(0))
    ref, _ = evolve_step(cfg, s0)
    sh_state = make_sharded_state(cfg, mesh, jax.random.key(0))
    got, _ = sharded_evolve_step(cfg, mesh, sh_state)
    np.testing.assert_array_equal(np.asarray(ref.weights), np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(got.uids))
    assert int(ref.time) == int(got.time)


def test_sharded_events_match_unsharded(mesh):
    cfg = SoupConfig(topo=WW, size=16, attacking_rate=0.5, learn_from_rate=0.3,
                     learn_from_severity=1, train=0)
    s0 = seed(cfg, jax.random.key(1))
    _, ev_ref = evolve_step(cfg, s0)
    _, ev_got = sharded_evolve_step(cfg, mesh, make_sharded_state(cfg, mesh, jax.random.key(1)))
    np.testing.assert_array_equal(np.asarray(ev_ref.action), np.asarray(ev_got.action))
    np.testing.assert_array_equal(np.asarray(ev_ref.counterpart), np.asarray(ev_got.counterpart))


def test_sharded_soup_full_run_with_respawn(mesh):
    """Full sharded soup with respawn: distributionally equivalent outcome
    (same class histogram shape, no NaN leakage, global uid monotonicity)."""
    cfg = SoupConfig(topo=WW, size=24, attacking_rate=0.3, learn_from_rate=-1,
                     train=5, remove_divergent=True, remove_zero=True)
    state = make_sharded_state(cfg, mesh, jax.random.key(2))
    final = sharded_evolve(cfg, mesh, state, generations=10)
    counts = sharded_count(cfg, mesh, final)
    assert int(counts.sum()) == 24
    assert int(final.time) == 10
    uids = np.asarray(final.uids)
    assert len(set(uids.tolist())) == 24  # all uids unique after respawns
    assert int(final.next_uid) >= 24


def test_sharded_popmajor_step_bitwise_matches_unsharded(mesh):
    """The sharded popmajor step vs single-device popmajor — attack,
    imitation (post-attack re-gather), train, respawn uids and fresh draws
    included.  Everything integer (uids, counters, events) is bitwise; the
    weights are ulp-tolerance: the per-lane math CAN'T reassociate across
    the lane split, but this XLA version fuses the narrower (P, N/D) shard
    program differently than the full-width one (<=2e-7 abs observed on
    XLA:CPU — same class as the documented compact-path contraction)."""
    cfg = SoupConfig(topo=WW, size=16, attacking_rate=0.5, learn_from_rate=0.3,
                     learn_from_severity=1, train=2, remove_divergent=True,
                     remove_zero=True, layout="popmajor")
    s0 = seed(cfg, jax.random.key(7))
    ref, ev_ref = evolve_step(cfg, s0)
    sh_state = make_sharded_state(cfg, mesh, jax.random.key(7))
    got, ev_got = sharded_evolve_step(cfg, mesh, sh_state)
    np.testing.assert_allclose(np.asarray(ref.weights), np.asarray(got.weights),
                               rtol=5e-5, atol=2e-6)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(got.uids))
    assert int(ref.next_uid) == int(got.next_uid)
    np.testing.assert_array_equal(np.asarray(ev_ref.action), np.asarray(ev_got.action))
    np.testing.assert_array_equal(np.asarray(ev_ref.counterpart),
                                  np.asarray(ev_got.counterpart))


def test_sharded_pallas_kernels_bitwise_match_unsharded(mesh):
    """The round-5 fused kernels inside the sharded body (per-shard
    pallas_call under shard_map) are bitwise vs the single-device kernels:
    the chains are per-lane elementwise, so the shard's narrower lane
    block cannot reassociate anything.  Recurrent soup takes BOTH kernel
    families (train BPTT + apply forward) in one step."""
    cfg = SoupConfig(topo=Topology("recurrent", width=2, depth=2), size=16,
                     attacking_rate=0.5, learn_from_rate=0.3,
                     learn_from_severity=1, train=2, remove_divergent=True,
                     remove_zero=True, layout="popmajor",
                     train_impl="pallas", apply_impl="pallas")
    s0 = seed(cfg, jax.random.key(9))
    ref, _ = evolve_step(cfg, s0)
    got, _ = sharded_evolve_step(cfg, mesh,
                                 make_sharded_state(cfg, mesh,
                                                    jax.random.key(9)))
    np.testing.assert_array_equal(np.asarray(ref.weights),
                                  np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(got.uids))
    assert int(ref.next_uid) == int(got.next_uid)


def test_sharded_popmajor_multigeneration_bitwise(mesh):
    """10 full-dynamics generations through the transposed-carry scan path
    equal the single-device popmajor evolve: integer state bit-for-bit,
    weights to compounded-ulp tolerance (this XLA version's shard-width
    fusion differences, ~2e-7/generation — see the step test above)."""
    from srnn_tpu.soup import evolve

    cfg = SoupConfig(topo=WW, size=24, attacking_rate=0.3, learn_from_rate=0.2,
                     learn_from_severity=1, train=3, remove_divergent=True,
                     remove_zero=True, layout="popmajor")
    s0 = seed(cfg, jax.random.key(8))
    ref = evolve(cfg, s0, generations=10)
    sh = sharded_evolve(cfg, mesh, make_sharded_state(cfg, mesh, jax.random.key(8)),
                        generations=10)
    np.testing.assert_allclose(np.asarray(ref.weights), np.asarray(sh.weights),
                               rtol=1e-4, atol=2e-6)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(sh.uids))
    assert int(ref.next_uid) == int(sh.next_uid)
    assert int(sh.time) == 10
    counts = sharded_count(cfg, mesh, sh)
    assert int(counts.sum()) == 24


def test_sharded_popmajor_compact_attack_matches_unsharded(mesh):
    """attack_impl='compact' under sharding: per-shard compaction against
    the all-gathered population.  uids/gates exact (same PRNG stream);
    weights to FMA-contraction tolerance (the compact block width differs
    from the full path's).  Sized so per-shard capacity < per-shard lanes
    — the compact branch genuinely runs on every shard."""
    from srnn_tpu.soup import _attack_capacity, evolve

    n_dev = mesh.devices.size
    cfg = SoupConfig(topo=WW, size=512 * n_dev, attacking_rate=0.05,
                     learn_from_rate=0.05, learn_from_severity=1,
                     train=1, remove_divergent=True, remove_zero=True,
                     layout="popmajor", respawn_draws="fused",
                     attack_impl="compact", learn_from_impl="compact")
    assert _attack_capacity(512, cfg.attacking_rate) < 512
    s0 = seed(cfg, jax.random.key(9))
    # one generation: the only difference is FMA contraction inside the
    # compact attack block -> ulp-tight
    ref1 = evolve(cfg._replace(attack_impl="full", learn_from_impl="full"),
                  s0, generations=1)
    sh1 = sharded_evolve(cfg, mesh,
                         make_sharded_state(cfg, mesh, jax.random.key(9)),
                         generations=1)
    np.testing.assert_array_equal(np.asarray(ref1.uids), np.asarray(sh1.uids))
    np.testing.assert_allclose(np.asarray(sh1.weights),
                               np.asarray(ref1.weights),
                               rtol=1e-4, atol=1e-6)
    # four generations: ulp seeds amplify through the train-phase dynamics
    # (sensitive directions grow); uids stay exact, weights stay close
    ref = evolve(cfg._replace(attack_impl="full", learn_from_impl="full"),
                 s0, generations=4)
    sh = sharded_evolve(cfg, mesh,
                        make_sharded_state(cfg, mesh, jax.random.key(9)),
                        generations=4)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(sh.uids))
    f, c = np.asarray(ref.weights), np.asarray(sh.weights)
    finite = np.isfinite(f).all(axis=1) & np.isfinite(c).all(axis=1)
    np.testing.assert_allclose(c[finite], f[finite], rtol=5e-3, atol=1e-6)


def test_sharded_rowmajor_rejects_compact_attack(mesh):
    cfg = SoupConfig(topo=WW, size=16, attacking_rate=0.3,
                     attack_impl="compact")
    with pytest.raises(ValueError, match="attack_impl"):
        sharded_evolve_step(cfg, mesh,
                            make_sharded_state(cfg, mesh, jax.random.key(0)))


def test_sharded_popmajor_aggregating_matches_unsharded(mesh):
    """All variants ride the sharded lane layout now; the aggregating soup's
    sharded popmajor step must match the single-device popmajor step
    (fence remains only for shuffler='random')."""
    from srnn_tpu import Topology
    from srnn_tpu.soup import evolve_step

    cfg = SoupConfig(topo=Topology("aggregating", width=2, depth=2),
                     size=16, attacking_rate=0.5, train=1,
                     remove_divergent=True, remove_zero=True,
                     layout="popmajor")
    s0 = seed(cfg, jax.random.key(9))
    ref, _ = evolve_step(cfg, s0)
    state = make_sharded_state(cfg, mesh, jax.random.key(9))
    got, _ = sharded_evolve_step(cfg, mesh, state)
    np.testing.assert_allclose(np.asarray(ref.weights), np.asarray(got.weights),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(got.uids))

    shuf_topo = Topology("aggregating", width=2, depth=2, shuffler="random")
    shuf_cfg = SoupConfig(topo=shuf_topo, size=16, layout="popmajor")
    shuf_state = make_sharded_state(shuf_cfg._replace(layout="rowmajor"), mesh,
                                    jax.random.key(9))
    with pytest.raises(ValueError):
        sharded_evolve_step(shuf_cfg, mesh, shuf_state)


def test_sharded_multisoup_step_matches_unsharded(mesh):
    """The sharded heterogeneous soup step — cross-type attacks included —
    matches evolve_multi_step under matched keys: integer state (uids,
    events, next_uid) EXACTLY; weights to reduction-reassociation tolerance
    (the agg/fft/rnn transforms' row-internal reductions tile differently
    at different batch shapes — see sharded_multisoup.py docstring)."""
    from srnn_tpu import Topology
    from srnn_tpu.multisoup import MultiSoupConfig, evolve_multi_step, seed_multi
    from srnn_tpu.parallel import (make_sharded_multi_state,
                                   sharded_evolve_multi_step)

    cfg = MultiSoupConfig(
        topos=(Topology("weightwise", width=2, depth=2),
               Topology("aggregating", width=2, depth=2),
               Topology("recurrent", width=2, depth=2)),
        sizes=(16, 8, 8),
        attacking_rate=0.5, learn_from_rate=0.3, learn_from_severity=1,
        train=1, remove_divergent=True, remove_zero=True)
    s0 = seed_multi(cfg, jax.random.key(11))
    ref, ev_ref = evolve_multi_step(cfg, s0)
    sh0 = make_sharded_multi_state(cfg, mesh, jax.random.key(11))
    got, ev_got = sharded_evolve_multi_step(cfg, mesh, sh0)
    for t in range(3):
        np.testing.assert_allclose(np.asarray(ref.weights[t]),
                                   np.asarray(got.weights[t]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(ref.uids[t]),
                                      np.asarray(got.uids[t]))
        np.testing.assert_array_equal(np.asarray(ev_ref.action[t]),
                                      np.asarray(ev_got.action[t]))
        np.testing.assert_array_equal(np.asarray(ev_ref.counterpart[t]),
                                      np.asarray(ev_got.counterpart[t]))
    assert int(ref.next_uid) == int(got.next_uid)


def test_sharded_multisoup_multigeneration(mesh):
    """Multi-generation sharded mixed soup: matches unsharded (weights to
    tolerance, uids exact), global counts conserved, uid monotonicity
    across cross-type respawns."""
    from srnn_tpu import Topology
    from srnn_tpu.multisoup import (MultiSoupConfig, count_multi, evolve_multi,
                                    seed_multi)
    from srnn_tpu.parallel import (make_sharded_multi_state,
                                   sharded_count_multi, sharded_evolve_multi)

    cfg = MultiSoupConfig(
        topos=(Topology("weightwise", width=2, depth=2),
               Topology("recurrent", width=2, depth=2)),
        sizes=(16, 8),
        attacking_rate=0.4, learn_from_rate=-1.0, train=2,
        remove_divergent=True, remove_zero=True)
    ref = evolve_multi(cfg, seed_multi(cfg, jax.random.key(12)), generations=8)
    sh = sharded_evolve_multi(
        cfg, mesh, make_sharded_multi_state(cfg, mesh, jax.random.key(12)),
        generations=8)
    for t in range(2):
        np.testing.assert_allclose(np.asarray(ref.weights[t]),
                                   np.asarray(sh.weights[t]),
                                   rtol=5e-4, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(ref.uids[t]),
                                      np.asarray(sh.uids[t]))
    counts = np.asarray(sharded_count_multi(cfg, mesh, sh))
    np.testing.assert_array_equal(counts, np.asarray(count_multi(cfg, ref)))
    assert counts.sum() == 24 and int(sh.time) == 8


def test_sharded_multisoup_rejects_indivisible_sizes(mesh):
    from srnn_tpu import Topology
    from srnn_tpu.multisoup import MultiSoupConfig
    from srnn_tpu.parallel import make_sharded_multi_state

    cfg = MultiSoupConfig(
        topos=(Topology("weightwise"), Topology("aggregating")),
        sizes=(16, 9))
    with pytest.raises(ValueError, match="divisible"):
        make_sharded_multi_state(cfg, mesh, jax.random.key(13))


def test_sharded_count_matches_local_count(mesh):
    cfg = SoupConfig(topo=WW, size=32, attacking_rate=0.0, learn_from_rate=0.0)
    s = seed(cfg, jax.random.key(3))
    local = count(cfg, s)
    sh = sharded_count(cfg, mesh, make_sharded_state(cfg, mesh, jax.random.key(3)))
    np.testing.assert_array_equal(np.asarray(local), np.asarray(sh))


def test_sharded_population_placement(mesh):
    pop = init_population(WW, jax.random.key(4), 16)
    sharded = shard_population(mesh, pop)
    assert sharded.sharding.spec == jax.sharding.PartitionSpec("soup")
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(pop))


def test_ring_rnn_matches_single_device(mesh):
    """Sequence-parallel RNN apply == serial scan, for a sequence length
    divisible by the mesh (T=1024 over 8 devices)."""
    topo = Topology("recurrent", width=4, depth=2)
    rng = np.random.default_rng(0)
    self_flat = jnp.asarray((rng.normal(size=topo.num_weights) * 0.3).astype(np.float32))
    t = 1024
    target = jnp.asarray(rng.normal(size=t).astype(np.float32))

    # serial reference on padded-to-T sequence via the variant's forward
    from srnn_tpu.nets.recurrent import forward
    expected = forward(topo, self_flat, target[:, None])[:, 0]

    got = ring_rnn_apply(topo, mesh, self_flat, shard_population(mesh, target))
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_ring_rnn_tanh(mesh):
    topo = Topology("recurrent", width=2, depth=2, activation="tanh")
    rng = np.random.default_rng(1)
    self_flat = jnp.asarray((rng.normal(size=topo.num_weights) * 0.3).astype(np.float32))
    target = jnp.asarray(rng.normal(size=64).astype(np.float32))
    from srnn_tpu.nets.recurrent import forward
    expected = forward(topo, self_flat, target[:, None])[:, 0]
    got = ring_rnn_apply(topo, mesh, self_flat, shard_population(mesh, target))
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_data_parallel_fixpoint_run_over_mesh(mesh):
    """run_fixpoint is embarrassingly parallel: jit with a sharded population
    compiles to per-device work without code changes (pjit auto-sharding)."""
    from srnn_tpu.engine import run_fixpoint

    pop = shard_population(mesh, init_population(WW, jax.random.key(5), 64))
    res = run_fixpoint(WW, pop, step_limit=20)
    assert int(res.counts.sum()) == 64


def test_ring_rnn_real_particle_odd_length(mesh):
    """The motivating workload: a real particle's weight sequence (P=17,
    odd, not divisible by 8 devices) — causal zero-padding must make this
    exact."""
    topo = Topology("recurrent", width=2, depth=2)
    rng = np.random.default_rng(2)
    self_flat = jnp.asarray((rng.normal(size=topo.num_weights) * 0.3).astype(np.float32))
    target = jnp.asarray(rng.normal(size=topo.num_weights).astype(np.float32))
    from srnn_tpu.nets.recurrent import forward
    expected = forward(topo, self_flat, target[:, None])[:, 0]
    got = ring_rnn_apply(topo, mesh, self_flat, target)
    assert got.shape == (17,)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-6)


# ------------------------------------------------- weight-axis sharding (SP)


@pytest.mark.parametrize("topo", [
    Topology("weightwise", width=4, depth=3),
    Topology("aggregating", width=5, depth=2, aggregates=4),
    Topology("aggregating", width=5, depth=2, aggregates=4, aggregator="max"),
    Topology("aggregating", width=5, depth=2, aggregates=4,
             aggregator="max_buggy"),
    Topology("fft", width=5, depth=2, aggregates=4),
    Topology("fft", width=5, depth=2, aggregates=4, fft_mode="rfft"),
    Topology("recurrent", width=3, depth=2, rnn_scan="associative"),
    Topology("recurrent", width=3, depth=2),  # dispatches to the ring
])
def test_sharded_apply_matches_single_device(mesh, topo):
    """Every weight-axis-sharded transform equals its single-device twin
    (P is odd for every one of these, so tail padding is exercised)."""
    from srnn_tpu.parallel.sharded_apply import sharded_apply_to_weights

    rng = np.random.default_rng(23)
    p = topo.num_weights
    assert p % mesh.devices.size != 0  # padding path active
    self_flat = jnp.asarray(rng.normal(size=p).astype(np.float32) * 0.3)
    target = jnp.asarray(rng.normal(size=p).astype(np.float32))
    want = np.asarray(apply_to_weights(topo, self_flat, target))
    got = np.asarray(sharded_apply_to_weights(topo, mesh, self_flat, target))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_sharded_apply_max_buggy_zero_quirk(mesh):
    """The sharded falsy-max must reproduce the reference quirk on the
    exact pathological case: a segment whose max would be 0.0 keeps its
    first element instead (network.py:303-308)."""
    from srnn_tpu.nets.aggregating import aggregate
    from srnn_tpu.parallel.sharded_apply import sharded_aggregating_apply

    topo = Topology("aggregating", width=2, depth=2, aggregator="max_buggy")
    p = topo.num_weights
    size = p // topo.aggregates
    vals = np.full(p, -1.0, np.float32)
    vals[size] = -5.0              # segment 1 first element
    vals[size + 1:2 * size] = 0.0  # zeros after it are falsy: never win
    flat = jnp.asarray(vals)
    want = np.asarray(aggregate(topo, flat))
    assert want[1] == -5.0  # the quirk fires
    got_full = sharded_aggregating_apply(topo, mesh, flat, flat)
    # compare through the full transform instead: aggregate feeds the MLP,
    # so equal aggregates <=> equal outputs for a fixed self net
    from srnn_tpu.nets.aggregating import apply as agg_apply
    np.testing.assert_allclose(np.asarray(got_full),
                               np.asarray(agg_apply(topo, flat, flat)),
                               rtol=1e-5, atol=1e-6)


def test_sharded_apply_unsupported_options_raise(mesh):
    """Only the random shuffler stays fenced (global permutation)."""
    from srnn_tpu.parallel.sharded_apply import (
        sharded_aggregating_apply, sharded_fft_apply)

    p = Topology("aggregating", width=2, depth=2).num_weights
    w = jnp.zeros(p)
    with pytest.raises(NotImplementedError):
        sharded_aggregating_apply(
            Topology("aggregating", shuffler="random"), mesh, w, w)
    with pytest.raises(NotImplementedError):
        sharded_fft_apply(
            Topology("fft", shuffler="random"), mesh, w, w)


def test_sharded_multisoup_popmajor_matches_unsharded(mesh):
    """The lane-major sharded mixed soup (per-type (P_t, N_t/D) shards,
    cross_apply_popmajor attacks) matches the unsharded popmajor path:
    integer state exactly, weights to reduction tolerance; multi-generation
    scan included."""
    from srnn_tpu import Topology
    from srnn_tpu.multisoup import (MultiSoupConfig, evolve_multi,
                                    evolve_multi_step, seed_multi)
    from srnn_tpu.parallel import (make_sharded_multi_state,
                                   sharded_evolve_multi,
                                   sharded_evolve_multi_step)

    cfg = MultiSoupConfig(
        topos=(Topology("weightwise", width=2, depth=2),
               Topology("aggregating", width=2, depth=2),
               Topology("recurrent", width=2, depth=2)),
        sizes=(16, 8, 8),
        attacking_rate=0.5, learn_from_rate=0.3, learn_from_severity=1,
        train=1, remove_divergent=True, remove_zero=True, layout="popmajor")
    s0 = seed_multi(cfg, jax.random.key(21))
    ref, ev_ref = evolve_multi_step(cfg, s0)
    sh0 = make_sharded_multi_state(cfg, mesh, jax.random.key(21))
    got, ev_got = sharded_evolve_multi_step(cfg, mesh, sh0)
    for t in range(3):
        # 2e-3: the shard-width fusion differences of this XLA version
        # compound through the imitation/train SGD chains (1.7e-4 max rel
        # observed on XLA:CPU); integer state below stays exact
        np.testing.assert_allclose(np.asarray(ref.weights[t]),
                                   np.asarray(got.weights[t]),
                                   rtol=2e-3, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(ref.uids[t]),
                                      np.asarray(got.uids[t]))
        np.testing.assert_array_equal(np.asarray(ev_ref.action[t]),
                                      np.asarray(ev_got.action[t]))
    assert int(ref.next_uid) == int(got.next_uid)

    ref8 = evolve_multi(cfg, s0, generations=6)
    sh8 = sharded_evolve_multi(cfg, mesh, sh0, generations=6)
    for t in range(3):
        np.testing.assert_allclose(np.asarray(ref8.weights[t]),
                                   np.asarray(sh8.weights[t]),
                                   rtol=5e-4, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(ref8.uids[t]),
                                      np.asarray(sh8.uids[t]))


def test_sharded_multisoup_pallas_kernels_match_unsharded(mesh):
    """The heterogeneous sharded step with the round-5 per-type fused
    kernels (train BPTT / SGD per type + the recurrent attacker's fused
    forward in the cross-type attack) matches the single-device multisoup
    with the same impls — the per-type dispatch resolves identically on
    both paths.  Weights to reduction tolerance (the aggregating
    attacker's lane matmul retiles with the shard width — same reason
    the XLA sibling test is not bitwise), integer state exact."""
    from srnn_tpu import Topology
    from srnn_tpu.multisoup import (MultiSoupConfig, evolve_multi_step,
                                    seed_multi)
    from srnn_tpu.parallel import (make_sharded_multi_state,
                                   sharded_evolve_multi_step)

    cfg = MultiSoupConfig(
        topos=(Topology("weightwise", width=2, depth=2),
               Topology("aggregating", width=2, depth=2),
               Topology("recurrent", width=2, depth=2)),
        sizes=(16, 8, 8),
        attacking_rate=0.5, learn_from_rate=0.3, learn_from_severity=1,
        train=1, remove_divergent=True, remove_zero=True,
        layout="popmajor", train_impl="pallas", apply_impl="pallas")
    s0 = seed_multi(cfg, jax.random.key(22))
    ref, _ = evolve_multi_step(cfg, s0)
    got, _ = sharded_evolve_multi_step(
        cfg, mesh, make_sharded_multi_state(cfg, mesh, jax.random.key(22)))
    for t in range(3):
        np.testing.assert_allclose(np.asarray(ref.weights[t]),
                                   np.asarray(got.weights[t]),
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_array_equal(np.asarray(ref.uids[t]),
                                      np.asarray(got.uids[t]))
    assert int(ref.next_uid) == int(got.next_uid)


def test_multislice_mesh_soup_bitwise_matches_single_device():
    """DCN tier (SURVEY §2.5 collective row): the SAME sharded-soup body
    runs on a (slices, particles) multislice mesh — the particle dim
    sharded over (DCN_AXIS, SOUP_AXIS) — and the popmajor layout matches
    the single-device step (integer state bitwise, weights to the
    shard-width fusion tolerance of this XLA version — see
    ``test_sharded_popmajor_step_bitwise_matches_unsharded``),
    multi-generation scan included."""
    from srnn_tpu.parallel import (make_sharded_state, multislice_soup_mesh,
                                   sharded_count, sharded_evolve,
                                   sharded_evolve_step)
    from srnn_tpu.soup import evolve, evolve_step

    mesh2 = multislice_soup_mesh(num_slices=2)
    from srnn_tpu.parallel.mesh import SOUP_AXIS
    from srnn_tpu.parallel.multihost import DCN_AXIS
    assert mesh2.axis_names == (DCN_AXIS, SOUP_AXIS)
    cfg = SoupConfig(topo=WW, size=24, attacking_rate=0.4,
                     learn_from_rate=0.3, learn_from_severity=1, train=1,
                     remove_divergent=True, remove_zero=True,
                     layout="popmajor")
    s0 = seed(cfg, jax.random.key(31))
    ref, _ = evolve_step(cfg, s0)
    got, _ = sharded_evolve_step(cfg, mesh2,
                                 make_sharded_state(cfg, mesh2,
                                                    jax.random.key(31)))
    np.testing.assert_allclose(np.asarray(ref.weights),
                               np.asarray(got.weights),
                               rtol=5e-5, atol=2e-6)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(got.uids))

    ref8 = evolve(cfg, s0, generations=8)
    sh8 = sharded_evolve(cfg, mesh2,
                         make_sharded_state(cfg, mesh2, jax.random.key(31)),
                         generations=8)
    np.testing.assert_allclose(np.asarray(ref8.weights),
                               np.asarray(sh8.weights),
                               rtol=5e-4, atol=1e-5)
    counts = sharded_count(cfg, mesh2, sh8)
    assert int(counts.sum()) == 24


def test_giant_particle_weight_axis_sharding(mesh):
    """Long-context substantiation (SURVEY §5): the weight-axis-sharded
    transforms handle particles orders of magnitude past the reference's
    14-17 weights.  Weightwise at P=17k (pure map) and the recurrent
    associative scan at a 20k-step sequence both match their single-device
    twins."""
    from srnn_tpu.nets.recurrent import forward as rnn_forward
    from srnn_tpu.parallel.sharded_apply import (rnn_associative_apply,
                                                 sharded_weightwise_apply)

    rng = np.random.default_rng(4)

    # weightwise: width=128 -> P = 4*128 + 128*128 + 128 = 17024 points
    big = Topology("weightwise", width=128, depth=2)
    p = big.num_weights
    assert p > 17_000
    self_flat = jnp.asarray(rng.normal(size=p).astype(np.float32) * 0.05)
    target = jnp.asarray(rng.normal(size=p).astype(np.float32))
    got = np.asarray(sharded_weightwise_apply(big, mesh, self_flat, target))
    want = np.asarray(apply_to_weights(big, self_flat, target))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    # recurrent: a 20_000-step target sequence through the distributed
    # associative scan vs the serial single-device scan
    rnn = Topology("recurrent", width=4, depth=2, rnn_scan="associative")
    t = 20_000
    rnn_flat = jnp.asarray(
        rng.normal(size=rnn.num_weights).astype(np.float32) * 0.2)
    seq = jnp.asarray(rng.normal(size=t).astype(np.float32) * 0.1)
    got = np.asarray(rnn_associative_apply(rnn, mesh, rnn_flat, seq))
    want = np.asarray(
        rnn_forward(rnn.with_(rnn_scan="sequential"),
                    rnn_flat, seq[:, None]))[:, 0]
    np.testing.assert_allclose(got, want, rtol=3e-3, atol=3e-4)
