"""Self-healing experiment service (PR 13): the recovery ladders.

The load-bearing contract is the quine framing (Chang & Lipson applied
to the service itself): after any perturbation the service reproduces
its own state — a kill -9 mid-load replays every admitted ticket from
the durable journal with results bitwise-equal to an uninterrupted run,
a poisoned tenant in a stacked group is bisect-quarantined while its
groupmates complete, admission control pushes back with typed overload
rejections the client backs off on, deadlines fail fast instead of
occupying stack slots, and SIGTERM drains gracefully into a resumable
journal.  Chaos events fire through the PRODUCTION admission/dispatch
paths (``resilience.chaos`` serve hooks), never test-only branches.

All in-process tests share ONE tiny fixpoint-density spelling
(trials=16, batch=16) so the compile cost is paid once; the subprocess
e2es are marked ``slow`` (tier-1 budget is tight).
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from srnn_tpu.resilience.chaos import (SERVE_FAULT_KINDS, ChaosMonkey,
                                       parse_schedule)
from srnn_tpu.serve import (DeadlineExpired, ExperimentService,
                            OverloadedError, ServiceClient,
                            ServiceOverloaded, TicketJournal, read_journal)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the one warm spelling every in-process test rides
PARAMS = {"trials": 16, "batch": 16}


def _submit(svc, seed, **kw):
    return svc.submit("fixpoint_density", dict(PARAMS, seed=seed), **kw)


# ---------------------------------------------------------------------------
# journal: round trip, torn tail, compaction
# ---------------------------------------------------------------------------


def test_journal_round_trip_and_torn_tail(tmp_path):
    j = TicketJournal(str(tmp_path))
    j.record_submit(ticket="t000001", kind="soup", params={"size": 8},
                    tenant="a", key="k1", deadline_wall=None, wall=1.0)
    j.record_submit(ticket="t000002", kind="soup", params={"size": 8},
                    tenant="b", key=None, deadline_wall=123.5, wall=2.0)
    j.record_done(["t000001"], "done")
    # the one artifact kill -9 mid-append can leave: a partial last line
    with open(j.path, "a") as f:
        f.write('{"e": "submit", "ticket": "t0000')
    unfinished, torn, nxt = read_journal(j.path)
    assert [e.ticket for e in unfinished] == ["t000002"]
    assert torn == 1 and nxt == 3
    assert unfinished[0].tenant == "b"
    assert unfinished[0].deadline_wall == 123.5
    # recover() compacts down to the unfinished suffix (atomic publish),
    # led by the ticket-counter watermark
    unfinished2, torn2, nxt2 = j.recover()
    assert [e.ticket for e in unfinished2] == ["t000002"]
    assert torn2 == 1 and nxt2 == 3
    rows = [json.loads(l) for l in open(j.path).read().splitlines()]
    assert [r["e"] for r in rows] == ["mark", "submit"]
    assert rows[0]["next_ticket"] == 3 and rows[1]["ticket"] == "t000002"
    # the reopened handle still appends (compaction must not strand it
    # writing to the replaced inode)
    j.record_done(["t000002"], "failed")
    j.close()
    unfinished3, _, nxt3 = read_journal(j.path)
    assert unfinished3 == [] and nxt3 == 3


def test_journal_watermark_survives_idle_restarts(tmp_path):
    """Compacting a fully-finished journal must NOT reset the ticket
    counter: a restart that serves no traffic, then another restart,
    would otherwise reissue already-used ids — colliding with earlier
    runs' telemetry rows and with stale clients' tickets."""
    j = TicketJournal(str(tmp_path))
    j.record_submit(ticket="t000005", kind="soup", params={}, tenant="a",
                    wall=1.0)
    j.record_done(["t000005"], "done")
    assert j.recover() == ([], 0, 6)
    j.close()
    # the idle restart cycle: nothing submitted, recover again
    j2 = TicketJournal(str(tmp_path))
    assert j2.recover() == ([], 0, 6)
    j2.close()


def test_service_journals_submits_and_dones(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"))
    with svc:
        t1 = _submit(svc, 0)
        # durable BEFORE dispatch: the journal already holds the submit
        unfinished, _, _ = read_journal(svc.journal.path)
        assert [e.ticket for e in unfinished] == [t1]
        svc.run_pending()
        unfinished, _, _ = read_journal(svc.journal.path)
        assert unfinished == []


def test_recover_replays_and_dedupes(tmp_path):
    root = str(tmp_path / "svc")
    svc = ExperimentService(root)
    tickets = [_submit(svc, i, idempotency_key=f"k{i}") for i in range(3)]
    svc.close()   # queued, never dispatched — the "crash"
    svc2 = ExperimentService(root)
    with svc2:
        assert svc2.recover() == 3
        # resubmit with a journaled key dedupes onto the replayed ticket
        assert _submit(svc2, 0, idempotency_key="k0") == tickets[0]
        svc2.run_pending()
        for t in tickets:
            assert svc2.wait(t, timeout_s=120)["status"] == "done"
        sh = svc2.stats()["self_healing"]
        assert sh["replayed"] == 3 and sh["journal_unfinished"] == 0
        # fresh ids continue past every journaled id (no reuse)
        assert _submit(svc2, 9) == "t000004"


# ---------------------------------------------------------------------------
# supervised dispatch: transient retries, poison-bisect quarantine
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fault", SERVE_FAULT_KINDS)
def test_transient_dispatch_fault_is_retried(tmp_path, fault):
    chaos = ChaosMonkey(parse_schedule(f"serve_dispatch_fault@1:{fault}"))
    svc = ExperimentService(str(tmp_path / "svc"), chaos=chaos,
                            retry_backoff_s=0.01)
    with svc:
        t = _submit(svc, 0)
        svc.run_pending()
        assert svc.poll(t)["status"] == "done"
        sh = svc.stats()["self_healing"]
        assert sh["dispatch_retries"] == 1 and sh["quarantined"] == 0
        assert svc.registry.counter("serve_dispatch_retries_total").value(
            kind="fixpoint_density", fault=fault) == 1


def test_poison_bisect_quarantines_only_the_poisoned(tmp_path):
    """K=4 stack, the 2nd admitted ticket poisoned: bisection isolates
    it (failed with the real error, quarantined) while the 3 innocents
    complete — with the same results a clean service produces."""
    chaos = ChaosMonkey(parse_schedule("serve_poison_tenant@2"))
    svc = ExperimentService(str(tmp_path / "svc"), max_stack=8,
                            chaos=chaos, retry_backoff_s=0.01)
    with svc:
        tickets = [_submit(svc, i) for i in range(4)]
        svc.run_pending()
        entries = [svc.poll(t) for t in tickets]
        assert [e["status"] for e in entries] == \
            ["done", "failed", "done", "done"]
        assert entries[1]["quarantined"] is True
        assert "poisoned" in entries[1]["error"]
        assert svc.stats()["self_healing"]["quarantined"] == 1
        svc.writer.flush()
        rows = [json.loads(l) for l in
                open(os.path.join(svc.root, "events.jsonl"))]
        assert any(r.get("kind") == "serve_bisect" for r in rows)
    # the innocents' results == a clean (chaos-free) service's results
    ref = ExperimentService(str(tmp_path / "ref"))
    with ref:
        rt = [_submit(ref, i) for i in (0, 2, 3)]
        ref.run_pending()
        for (i, t) in zip((0, 2, 3), rt):
            assert entries[i]["result"] == ref.poll(t)["result"]


def test_deterministic_fatal_fault_is_not_retried(tmp_path):
    """A bad config (FATAL by the taxonomy) must not burn retries — the
    solo request fails once, immediately."""
    svc = ExperimentService(str(tmp_path / "svc"), dispatch_retries=3)
    with svc:
        t = svc.submit("soup", {"size": 8, "generations": 2,
                                "train_mode": "bogus"})
        svc.run_pending()
        e = svc.poll(t)
        assert e["status"] == "failed" and "bogus" in e["error"]
        assert "quarantined" not in e
        assert svc.stats()["self_healing"]["dispatch_retries"] == 0


# ---------------------------------------------------------------------------
# admission control, deadlines, retention
# ---------------------------------------------------------------------------


def test_overload_rejection_in_process(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"), max_queue=2)
    with svc:
        _submit(svc, 0), _submit(svc, 1)
        with pytest.raises(OverloadedError, match="max_queue"):
            _submit(svc, 2)
        sh = svc.stats()["self_healing"]
        assert sh["overload_rejections"] == 1
        assert svc.registry.gauge("serve_queue_rejected_depth").value() == 2
        svc.run_pending()
        _submit(svc, 2)   # drained queue admits again


def test_deadline_enforced_at_admission_and_dispatch(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"))
    with svc:
        with pytest.raises(DeadlineExpired):
            _submit(svc, 0, deadline_s=0)
        t1 = _submit(svc, 1, deadline_s=0.01)
        t2 = _submit(svc, 2, deadline_s=600.0)
        time.sleep(0.05)
        svc.run_pending()
        e1, e2 = svc.poll(t1), svc.poll(t2)
        assert e1["status"] == "failed" and "deadline" in e1["error"]
        assert e2["status"] == "done"
        assert svc.stats()["self_healing"]["deadline_expirations"] == 2
        # the expired ticket is journaled done (failed): no replay
        assert read_journal(svc.journal.path)[0] == []


def test_results_ttl_eviction(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"), results_ttl_s=0.05)
    with svc:
        t1 = _submit(svc, 0)
        svc.run_pending()
        assert svc.poll(t1) is not None
        time.sleep(0.1)
        t2 = _submit(svc, 1)
        svc.run_pending()   # the publish sweep evicts the stale entry
        assert svc.poll(t1) is None
        assert svc.poll(t2) is not None
        assert svc.stats()["self_healing"]["results_evicted"] == 1


def test_idempotency_window_closes_on_consume(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"))
    with svc:
        t1 = _submit(svc, 0, idempotency_key="k")
        assert _submit(svc, 0, idempotency_key="k") == t1
        svc.run_pending()
        assert _submit(svc, 0, idempotency_key="k") == t1  # uncollected
        svc.wait(t1, timeout_s=60)
        t2 = _submit(svc, 0, idempotency_key="k")  # consumed -> fresh run
        assert t2 != t1


# ---------------------------------------------------------------------------
# socket transport: typed overload + client backoff, drain-resume
# ---------------------------------------------------------------------------


def _start_server(svc, sock, window_s):
    from srnn_tpu.serve.server import ServiceServer
    from srnn_tpu.utils.pipeline import spawn_thread

    server = ServiceServer(svc, sock, batch_window_s=window_s)
    thread = spawn_thread(server.serve_until_shutdown, name="test-serve")
    ServiceClient(sock).wait_until_up(30)
    return server, thread


def test_socket_overload_typed_and_client_backoff(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"), max_queue=1)
    sock = str(tmp_path / "serve.sock")
    _server, thread = _start_server(svc, sock, window_s=0.05)
    try:
        plain = ServiceClient(sock)
        saw_overload = False
        for i in range(200):   # submits outpace the 0.05s window
            try:
                plain.submit("fixpoint_density", dict(PARAMS, seed=i))
            except ServiceOverloaded:
                saw_overload = True
                break
        assert saw_overload
        # a retrying client rides the pushback out with seeded backoff
        patient = ServiceClient(sock, retries=10, backoff_base_s=0.05,
                                seed=3)
        res = patient.request("fixpoint_density", dict(PARAMS, seed=99),
                              timeout_s=120)
        assert len(res["counters"]) == 2
        assert plain.stats()["self_healing"]["overload_rejections"] >= 1
    finally:
        ServiceClient(sock).shutdown()
        thread.join(timeout=60)
        svc.close()


def test_socket_drain_keeps_journal_and_resumes(tmp_path):
    """The drain op (the socket spelling of SIGTERM): queued tickets
    resolve as typed-resumable failures, stay journaled-unfinished, and
    a fresh service on the same root replays them to completion."""
    root = str(tmp_path / "svc")
    svc = ExperimentService(root, max_stack=8)
    sock = str(tmp_path / "serve.sock")
    _server, thread = _start_server(svc, sock, window_s=1.0)
    client = ServiceClient(sock)
    try:
        tickets = [client.submit("fixpoint_density", dict(PARAMS, seed=i),
                                 idempotency_key=f"k{i}")
                   for i in range(3)]
        client.drain()   # lands inside the 1s batching window
    finally:
        thread.join(timeout=60)
        svc.close()
    unfinished, _, _ = read_journal(os.path.join(root, "journal.jsonl"))
    assert [e.ticket for e in unfinished] == tickets
    svc2 = ExperimentService(root)
    with svc2:
        assert svc2.recover() == 3
        svc2.run_pending()
        for t in tickets:
            assert svc2.wait(t, timeout_s=120)["status"] == "done"


def test_client_backoff_is_deterministic():
    a = ServiceClient("/nonexistent", seed=7)
    b = ServiceClient("/nonexistent", seed=7)
    assert [a._policy.delay(k) for k in range(4)] == \
        [b._policy.delay(k) for k in range(4)]
    c = ServiceClient("/nonexistent", seed=8)
    assert [a._policy.delay(k) for k in range(4)] != \
        [c._policy.delay(k) for k in range(4)]


def test_client_never_retries_keyless_submit_after_delivery_risk(tmp_path):
    """A mid-op connection death AFTER the op may have reached the
    service must not be retried for a keyless submit (it could
    double-run admitted work); with an idempotency key — or for pure
    reads — the retry is safe and taken."""
    from srnn_tpu.serve.client import _retry_is_safe

    assert not _retry_is_safe({"op": "submit", "kind": "soup"})
    assert not _retry_is_safe({"op": "request", "kind": "soup"})
    assert _retry_is_safe({"op": "submit", "idempotency_key": "k"})
    assert _retry_is_safe({"op": "wait", "ticket": "t000001"})
    assert _retry_is_safe({"op": "stats"})

    calls = []

    class _Boom(ServiceClient):
        def _op_once(self, msg, timeout_s=None):
            calls.append(msg["op"])
            raise ConnectionResetError("mid-op")

    c = _Boom(str(tmp_path / "x.sock"), retries=3, backoff_base_s=0.001)
    with pytest.raises(ConnectionResetError):
        c._op({"op": "submit", "kind": "soup"})
    assert len(calls) == 1            # keyless submit: no retry
    calls.clear()
    with pytest.raises(ConnectionResetError):
        c._op({"op": "submit", "kind": "soup", "idempotency_key": "k"})
    assert len(calls) == 4            # keyed: full retry budget


def test_client_retries_connection_refused(tmp_path):
    sock = str(tmp_path / "nope.sock")
    client = ServiceClient(sock, retries=2, backoff_base_s=0.01)
    t0 = time.monotonic()
    with pytest.raises((OSError, ServiceOverloaded)):
        client.stats()
    assert time.monotonic() - t0 >= 0.02   # two backoffs were taken


# ---------------------------------------------------------------------------
# chaos schedule: serve kinds parse/validate
# ---------------------------------------------------------------------------


def test_serve_chaos_schedule_validation():
    evs = parse_schedule("serve_kill@1,serve_dispatch_fault@2:stall,"
                         "serve_poison_tenant@3")
    assert [e.kind for e in evs] == ["serve_kill", "serve_dispatch_fault",
                                    "serve_poison_tenant"]
    assert evs[1].arg == "stall"
    assert parse_schedule("serve_dispatch_fault@1")[0].arg == "io"
    with pytest.raises(ValueError, match="1-based"):
        parse_schedule("serve_kill@0")
    with pytest.raises(ValueError, match="one of"):
        parse_schedule("serve_dispatch_fault@1:bogus")
    with pytest.raises(ValueError, match="serve_dispatch_fault"):
        parse_schedule("device_loss@3:io")


# ---------------------------------------------------------------------------
# subprocess e2es (slow): kill -9 bitwise replay, SIGTERM drain-resume
# ---------------------------------------------------------------------------


def _serve_env():
    env = dict(os.environ)
    env["SRNN_SETUPS_PLATFORM"] = "cpu"
    env.pop("PYTHONPATH", None)
    return env


def _spawn_service(root, log, *extra):
    return subprocess.Popen(
        [sys.executable, "-m", "srnn_tpu.serve", "--root", root] +
        list(extra), cwd=REPO, env=_serve_env(),
        stdout=log, stderr=subprocess.STDOUT)


def _wait_up(root, timeout_s=90):
    ServiceClient(os.path.join(root, "serve.sock")).wait_until_up(timeout_s)


@pytest.mark.slow
def test_kill9_restart_replays_bitwise(tmp_path):
    """The acceptance e2e: kill -9 the service with 8 admitted tickets
    queued, restart, and every ticket completes under its ORIGINAL id
    with results bitwise-equal to an uninterrupted run."""
    seeds = list(range(8))
    log = open(str(tmp_path / "serve.log"), "w")

    # uninterrupted reference run
    ref_root = str(tmp_path / "ref")
    proc = _spawn_service(ref_root, log, "--batch-window-s", "0.1")
    try:
        _wait_up(ref_root)
        client = ServiceClient(os.path.join(ref_root, "serve.sock"))
        tickets = [client.submit("fixpoint_density",
                                 dict(PARAMS, seed=s)) for s in seeds]
        reference = [client.wait(t, timeout_s=240) for t in tickets]
        client.shutdown()
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()

    # chaos run: the injector SIGKILLs the process at the 1st dispatch —
    # all 8 tickets are journaled (acknowledged) but unfinished
    root = str(tmp_path / "svc")
    proc = _spawn_service(root, log, "--batch-window-s", "2",
                          "--chaos", "serve_kill@1")
    try:
        _wait_up(root)
        client = ServiceClient(os.path.join(root, "serve.sock"))
        tickets = [client.submit("fixpoint_density", dict(PARAMS, seed=s),
                                 idempotency_key=f"e2e-{s}")
                   for s in seeds]
        assert proc.wait(timeout=120) == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
    unfinished, _, _ = read_journal(os.path.join(root, "journal.jsonl"))
    assert [e.ticket for e in unfinished] == tickets

    # restart on the same root: replay completes every admitted ticket
    proc = _spawn_service(root, log, "--batch-window-s", "0.1")
    try:
        _wait_up(root)
        client = ServiceClient(os.path.join(root, "serve.sock"),
                               retries=3, backoff_base_s=0.1)
        # resubmit-after-restart dedupes against the journal: the SAME
        # ticket comes back instead of a double-run
        assert client.submit("fixpoint_density", dict(PARAMS, seed=0),
                             idempotency_key="e2e-0") == tickets[0]
        replayed = [client.wait(t, timeout_s=240) for t in tickets]
        for got, want in zip(replayed, reference):
            assert got == want   # bitwise: integer counters, exact dicts
        stats = client.stats()
        assert stats["self_healing"]["replayed"] == 8
        client.shutdown()
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
    prom = open(os.path.join(root, "metrics.prom")).read()
    assert "srnn_serve_journal_replays_total 8" in prom


@pytest.mark.slow
def test_sigterm_drain_resume(tmp_path):
    """SIGTERM mid-window: the service exits 0 WITHOUT dispatching the
    queue, the tickets stay journaled, and a restart resumes them."""
    root = str(tmp_path / "svc")
    log = open(str(tmp_path / "serve.log"), "w")
    proc = _spawn_service(root, log, "--batch-window-s", "5")
    try:
        _wait_up(root)
        client = ServiceClient(os.path.join(root, "serve.sock"))
        tickets = [client.submit("fixpoint_density", dict(PARAMS, seed=s),
                                 idempotency_key=f"d-{s}")
                   for s in range(6)]
        proc.send_signal(signal.SIGTERM)   # lands inside the 5s window
        assert proc.wait(timeout=60) == 0  # graceful drain exits clean
    finally:
        if proc.poll() is None:
            proc.kill()
    unfinished, _, _ = read_journal(os.path.join(root, "journal.jsonl"))
    assert [e.ticket for e in unfinished] == tickets

    proc = _spawn_service(root, log, "--batch-window-s", "0.1")
    try:
        _wait_up(root)
        client = ServiceClient(os.path.join(root, "serve.sock"))
        for t in tickets:
            assert client.wait(t, timeout_s=240) is not None
        assert client.stats()["self_healing"]["replayed"] == 6
        client.shutdown()
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
