"""AOT precompile + buffer-donation subsystem (``srnn_tpu.utils.aot``).

Donation must be a pure memory optimization — same bits out of the donated
and non-donated spellings — and the AOT executable memo must hit on a
repeated (topology, config, shapes, backend) key and miss when the
topology changes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import engine, multisoup, soup
from srnn_tpu.soup import SoupConfig, seed
from srnn_tpu.topology import Topology
from srnn_tpu.utils import aot

WW = Topology("weightwise", width=2, depth=2)
AGG = Topology("aggregating", width=2, depth=2)
RNN = Topology("recurrent", width=2, depth=2)


def _full_dynamics(topo, **over):
    kw = dict(topo=topo, size=16, attacking_rate=0.3, learn_from_rate=0.3,
              train=1, remove_divergent=True, remove_zero=True)
    kw.update(over)
    return SoupConfig(**kw)


def _assert_states_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.weights), np.asarray(b.weights))
    np.testing.assert_array_equal(np.asarray(a.uids), np.asarray(b.uids))
    assert int(a.next_uid) == int(b.next_uid)
    assert int(a.time) == int(b.time)
    np.testing.assert_array_equal(np.asarray(jax.random.key_data(a.key)),
                                  np.asarray(jax.random.key_data(b.key)))


@pytest.mark.parametrize("topo", [WW, AGG, RNN],
                         ids=lambda t: t.variant)
def test_donated_step_bitwise_parity(topo):
    """The donated step is the SAME program: bitwise-equal states over 3
    full-dynamics generations for every variant."""
    cfg = _full_dynamics(topo)
    ref = seed(cfg, jax.random.key(3))
    don = jax.tree.map(jnp.copy, ref)
    for _ in range(3):
        ref, ev_ref = soup.evolve_step(cfg, ref)
        don, ev_don = soup.evolve_step_donated(cfg, don)
        np.testing.assert_array_equal(np.asarray(ev_ref.action),
                                      np.asarray(ev_don.action))
    _assert_states_equal(ref, don)


def test_donated_evolve_popmajor_parity():
    """Popmajor mega-config: donated vs plain multi-generation run.  XLA
    may fuse the aliased program differently (same class of <=1-ulp
    reassociation the compact paths document), so the weights tolerance is
    ulp-scale rather than bitwise; uids/counters stay exact."""
    cfg = _full_dynamics(WW, layout="popmajor", respawn_draws="fused")
    st = seed(cfg, jax.random.key(5))
    ref = soup.evolve(cfg, st, generations=3)
    don = soup.evolve_donated(cfg, jax.tree.map(jnp.copy, st), generations=3)
    np.testing.assert_array_equal(np.asarray(ref.uids), np.asarray(don.uids))
    assert int(ref.next_uid) == int(don.next_uid)
    np.testing.assert_allclose(np.asarray(ref.weights),
                               np.asarray(don.weights), rtol=2e-6, atol=1e-7)


def test_donated_input_is_consumed():
    """Contract check: the donated step really donates — the input state's
    buffers are dead afterwards (this is what frees the second
    population-sized buffer at mega scale)."""
    cfg = _full_dynamics(WW, learn_from_rate=-1.0, train=0)
    st = seed(cfg, jax.random.key(0))
    _ = soup.evolve_step_donated(cfg, st)
    with pytest.raises((RuntimeError, ValueError)):
        np.asarray(st.weights)  # donated buffer must be unusable


def test_donated_multisoup_step_parity():
    mcfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(8, 8), attacking_rate=0.4,
        learn_from_rate=0.3, train=1, remove_divergent=True,
        remove_zero=True)
    ref = multisoup.seed_multi(mcfg, jax.random.key(2))
    don = jax.tree.map(jnp.copy, ref)
    for _ in range(3):
        ref, _ev = multisoup.evolve_multi_step(mcfg, ref)
        don, _ev2 = multisoup.evolve_multi_step_donated(mcfg, don)
    for t in range(2):
        np.testing.assert_array_equal(np.asarray(ref.weights[t]),
                                      np.asarray(don.weights[t]))
        np.testing.assert_array_equal(np.asarray(ref.uids[t]),
                                      np.asarray(don.uids[t]))


def test_donated_engine_parity():
    from srnn_tpu.init import init_population

    pop = init_population(WW, jax.random.key(1), 12)
    ref = engine.run_fixpoint(WW, pop, step_limit=4)
    don = engine.run_fixpoint_donated(WW, jnp.copy(pop), step_limit=4)
    np.testing.assert_array_equal(np.asarray(ref.weights),
                                  np.asarray(don.weights))
    np.testing.assert_array_equal(np.asarray(ref.steps), np.asarray(don.steps))

    ref = engine.run_training(WW, pop, epochs=3)
    don = engine.run_training_donated(WW, jnp.copy(pop), epochs=3)
    np.testing.assert_array_equal(np.asarray(ref.weights),
                                  np.asarray(don.weights))
    np.testing.assert_array_equal(np.asarray(ref.losses), np.asarray(don.losses))


# --------------------------------------------------------------- AOT memo


def test_aot_cache_hit_same_key_and_miss_on_topology_change():
    aot.clear_executable_cache()
    cfg = SoupConfig(topo=WW, size=8, attacking_rate=0.2,
                     remove_divergent=True, remove_zero=True)
    rows = aot.warmup(cfg, generations=2)
    assert rows and not any(r["cached"] for r in rows)
    again = aot.warmup(cfg, generations=2)
    assert [r["entry"] for r in again] == [r["entry"] for r in rows]
    assert all(r["cached"] for r in again)
    assert all(r["compile_s"] == 0.0 for r in again)

    # same shapes, different topology -> different key -> fresh compiles
    miss = aot.warmup(cfg._replace(topo=AGG), generations=2)
    assert not any(r["cached"] for r in miss)
    # a config change that alters the compiled program also misses
    miss2 = aot.warmup(cfg._replace(attacking_rate=0.5), generations=2)
    assert not any(r["cached"] for r in miss2)


def test_aot_compiled_executable_runs_and_matches_jit():
    aot.clear_executable_cache()
    cfg = SoupConfig(topo=WW, size=8, attacking_rate=0.3,
                     remove_divergent=True, remove_zero=True)
    entry = aot.aot_compile("test.evolve_step", soup.evolve_step,
                            (cfg, aot.abstract_soup_state(cfg)))
    st = seed(cfg, jax.random.key(7))
    ref, _ = soup.evolve_step(cfg, st)
    got, _ = entry.compiled(st)
    _assert_states_equal(ref, got)


def test_donation_aliases_population_buffer():
    """``memory_analysis`` proof that the donated step emits no second
    population-sized output buffer: the whole argument block (population
    included) aliases the outputs, while the plain step aliases nothing."""
    cfg = SoupConfig(topo=WW, size=4096, attacking_rate=0.1,
                     remove_divergent=True, remove_zero=True,
                     layout="popmajor", respawn_draws="fused")
    pop_bytes = cfg.size * cfg.topo.num_weights * 4
    st = aot.abstract_soup_state(cfg)
    # persistent=False: a cache-deserialized executable reports empty
    # memory stats, so the aliasing proof must compile fresh
    don = aot.aot_compile("test.mem.donated", soup.evolve_step_donated,
                          (cfg, st),
                          persistent=False).compiled.memory_analysis()
    plain = aot.aot_compile("test.mem.plain", soup.evolve_step,
                            (cfg, st),
                            persistent=False).compiled.memory_analysis()
    assert don.alias_size_in_bytes >= pop_bytes
    assert plain.alias_size_in_bytes < pop_bytes


def test_engine_and_multi_warmup_entries():
    aot.clear_executable_cache()
    cfg = SoupConfig(topo=WW, size=8, attacking_rate=0.2,
                     remove_divergent=True, remove_zero=True)
    mcfg = multisoup.MultiSoupConfig(topos=(WW, AGG), sizes=(8, 8),
                                     attacking_rate=0.2, learn_from_rate=-1.0,
                                     remove_divergent=True, remove_zero=True)
    rows = aot.warmup(cfg, multi=mcfg, generations=2, engine=True,
                      step_limit=2, epochs=2)
    entries = {r["entry"] for r in rows}
    assert "soup.evolve_step.donated" in entries
    assert "multisoup.evolve_multi.donated" in entries
    assert "engine.run_fixpoint.donated" in entries
    assert "engine.run_training.donated" in entries
    # non-donating sweep compiles the value-preserving spellings separately
    # (plus the telemetry-metered chunk run the production loops dispatch,
    # with/without the flight recorder's health sentinels and with the
    # replication-dynamics lineage carry)
    plain = aot.warmup(cfg, generations=2, donate=False)
    assert {r["entry"] for r in plain} == {
        "soup.evolve_step", "soup.evolve", "soup.evolve.metered",
        "soup.evolve.metered.health", "soup.evolve.metered.health.lineage",
        "soup.evolve.metered.lineage"}
    assert not any(r["cached"] for r in plain)


def test_warmup_fused_spellings_for_popmajor_configs():
    """A fused-eligible popmajor config's warmup ALSO pre-builds the
    ``generation_impl='fused'`` twins (their own programs — precompile
    must cover them or a fused run's first chunk pays full compile inside
    the bench deadline); rowmajor configs get none (fused is popmajor-only
    and the entry would be a dead executable)."""
    aot.clear_executable_cache()
    cfg = SoupConfig(topo=WW, size=8, attacking_rate=0.2,
                     remove_divergent=True, remove_zero=True,
                     layout="popmajor")
    rows = aot.warmup(cfg, generations=2, donate=False)
    assert {r["entry"] for r in rows} == {
        "soup.evolve_step", "soup.evolve", "soup.evolve.metered",
        "soup.evolve.metered.health", "soup.evolve.metered.health.lineage",
        "soup.evolve.metered.lineage",
        "soup.evolve_step.fused", "soup.evolve.fused",
        "soup.evolve.fused.metered.health"}
    # a config that is ALREADY fused warms its own (fused) programs under
    # the base names — no duplicate .fused twins
    fused_rows = aot.warmup(cfg._replace(generation_impl="fused"),
                            generations=2, donate=False)
    assert not any(".fused" in r["entry"] for r in fused_rows)
    # rowmajor (the engine/parity default): no fused spellings
    rm = aot.warmup(cfg._replace(layout="rowmajor"), generations=2,
                    donate=False)
    assert not any(".fused" in r["entry"] for r in rm)


def test_warmup_fused_spellings_for_multi():
    aot.clear_executable_cache()
    mcfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(8, 8), attacking_rate=0.2,
        learn_from_rate=-1.0, remove_divergent=True, remove_zero=True,
        layout="popmajor")
    rows = aot.warmup(None, multi=mcfg, generations=2, donate=False)
    entries = {r["entry"] for r in rows}
    assert "multisoup.evolve_multi_step.fused" in entries
    assert "multisoup.evolve_multi.fused" in entries
    assert "multisoup.evolve_multi.fused.metered.health" in entries


def test_warmup_sharded_entries_accept_mesh():
    """A Mesh argument has .shape but no .dtype — the abstraction step
    must pass it through as a static, not explode on it."""
    from srnn_tpu.parallel import soup_mesh
    from srnn_tpu.parallel.sharded_soup import sharded_evolve_step_donated

    mesh = soup_mesh()
    cfg = SoupConfig(topo=WW, size=mesh.devices.size * 2, attacking_rate=0.2,
                     remove_divergent=True, remove_zero=True)
    entry = aot.aot_compile("test.sharded.step", sharded_evolve_step_donated,
                            (cfg, mesh, aot.abstract_soup_state(cfg)))
    assert entry.compiled is not None
