"""Replication-dynamics observatory (telemetry.dynamics / genealogy).

Four layers of coverage:

  * **bit-identity** — ``lineage=True`` leaves the evolved population
    bit-identical to the plain program on both layouts, the multisoup,
    and the sharded twins (the same guarantee the metrics/health carries
    give).
  * **NumPy recount** — the device-side pid minting and event-edge
    buffers are recomputed on host from an independent replay of the
    step's phase draws (gates/targets from the same key-split structure,
    deaths from the uid trail) and must match exactly.
  * **sharded parity** — globally-unique pids everywhere; the popmajor
    sharded path assigns BIT-IDENTICAL pids/edges to the single-device
    run (the documented lineage extension of its bitwise contract).
  * **host round-trip** — events -> lineage.jsonl -> genealogy forest ->
    ``report --dynamics`` renders a dominant-lineage table and fixpoint
    census from a real ``mega_soup`` run end to end, and the resume
    sidecar continues the pid epoch.
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import multisoup, soup
from srnn_tpu.telemetry import dynamics, genealogy, report
from srnn_tpu.topology import Topology

WW = Topology("weightwise", width=2, depth=2)
AGG = Topology("aggregating", width=2, depth=2, aggregates=4)


def _cfg(layout="popmajor", **kw):
    kw.setdefault("respawn_draws",
                  "fused" if layout == "popmajor" else "perparticle")
    return soup.SoupConfig(
        topo=WW, size=64, attacking_rate=0.3, learn_from_rate=0.2, train=0,
        remove_divergent=True, remove_zero=True, layout=layout, **kw)


def _evolve_lineage(cfg, st, gens, cap=512):
    lin = dynamics.seed_lineage(cfg.size, time=int(st.time))
    return soup.evolve(cfg, st, generations=gens, lineage=True,
                       lineage_state=lin, lineage_capacity=cap)


# --------------------------------------------------------------- identity


@pytest.mark.parametrize("layout", ["rowmajor", "popmajor"])
def test_lineage_state_bit_identical(layout):
    cfg = _cfg(layout)
    st = soup.seed(cfg, jax.random.key(0))
    plain = soup.evolve(cfg, st, generations=5)
    final, (lin, win, stats) = _evolve_lineage(cfg, st, 5)
    np.testing.assert_array_equal(np.asarray(plain.weights),
                                  np.asarray(final.weights))
    np.testing.assert_array_equal(np.asarray(plain.uids),
                                  np.asarray(final.uids))
    assert int(plain.next_uid) == int(final.next_uid)
    # metrics/health spellings compose with lineage unchanged
    m_plain = soup.evolve(cfg, st, generations=5, metrics=True)[1]
    out = soup.evolve(cfg, st, generations=5, metrics=True, health=True,
                      lineage=True,
                      lineage_state=dynamics.seed_lineage(cfg.size),
                      lineage_capacity=512)
    np.testing.assert_array_equal(np.asarray(m_plain.actions),
                                  np.asarray(out[1].actions))
    np.testing.assert_array_equal(np.asarray(out[0].weights),
                                  np.asarray(final.weights))


def test_lineage_requires_parallel_mode_and_state():
    cfg = _cfg("rowmajor")._replace(mode="sequential")
    st = soup.seed(cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="parallel"):
        soup.evolve(cfg, st, generations=1, lineage=True,
                    lineage_state=dynamics.seed_lineage(cfg.size))
    with pytest.raises(ValueError, match="lineage_state"):
        soup.evolve(_cfg(), st, generations=1, lineage=True)


# ---------------------------------------------------------- NumPy recount


def _replay_masks(cfg, state):
    """Independently re-derive one generation's phase draws from the
    state's key (the step's exact split structure)."""
    n = cfg.size
    _key, k_ag, k_at, k_lg, k_lt, _k_re = jax.random.split(state.key, 6)
    attack_gate = np.asarray(jax.random.uniform(k_ag, (n,))
                             < cfg.attacking_rate)
    attack_tgt = np.asarray(jax.random.randint(k_at, (n,), 0, n))
    att_idx = np.full(n, -1, np.int64)
    for lane in range(n):  # last-attacker-wins, by construction
        if attack_gate[lane]:
            att_idx[attack_tgt[lane]] = max(att_idx[attack_tgt[lane]], lane)
    learn_gate = np.asarray(jax.random.uniform(k_lg, (n,))
                            < cfg.learn_from_rate)
    learn_tgt = np.asarray(jax.random.randint(k_lt, (n,), 0, n))
    return att_idx, learn_gate, learn_tgt


@pytest.mark.parametrize("layout", ["rowmajor", "popmajor"])
def test_lineage_numpy_recount(layout):
    """Full host recount of the pid mints + edge stream: replay the phase
    draws, walk the uid trail for deaths, and rebuild every window row."""
    cfg = _cfg(layout)
    n, gens = cfg.size, 5
    st = soup.seed(cfg, jax.random.key(3))
    # ground-truth state trail, one generation at a time
    states = [st]
    for _ in range(gens):
        states.append(soup.evolve(cfg, states[-1], generations=1))

    pid = np.arange(n, dtype=np.int64)
    parent = np.full(n, -1, np.int64)
    birth = np.zeros(n, np.int64)
    next_pid = n
    edges = []
    for t in range(gens):
        att_idx, learn_gate, learn_tgt = _replay_masks(cfg, states[t])
        dead = (np.asarray(states[t].uids)
                != np.asarray(states[t + 1].uids))
        old = pid.copy()
        # attack mints, lane order
        for lane in np.nonzero(att_idx >= 0)[0]:
            src = old[att_idx[lane]]
            pid[lane] = next_pid
            parent[lane] = src
            birth[lane] = t
            next_pid += 1
            edges.append([dynamics.EDGE_ATTACK, t, src, pid[lane],
                          old[lane]])
        mid = pid.copy()
        for lane in np.nonzero(learn_gate)[0]:
            edges.append([dynamics.EDGE_LEARN, t, mid[learn_tgt[lane]],
                          mid[lane], -1])
        for lane in np.nonzero(dead)[0]:
            pid[lane] = next_pid
            parent[lane] = -1
            birth[lane] = t
            next_pid += 1
            edges.append([dynamics.EDGE_RESPAWN, t, -1, pid[lane],
                          mid[lane]])

    final, (lin, win, _stats) = _evolve_lineage(cfg, st, gens, cap=2048)
    np.testing.assert_array_equal(np.asarray(final.weights),
                                  np.asarray(states[-1].weights))
    np.testing.assert_array_equal(np.asarray(lin.pid), pid)
    np.testing.assert_array_equal(np.asarray(lin.parent), parent)
    np.testing.assert_array_equal(np.asarray(lin.birth), birth)
    assert int(lin.next_pid) == next_pid
    got = dynamics.window_edge_rows(win, 2048)
    assert got == edges
    assert int(np.asarray(win.dropped).sum()) == 0
    births = np.asarray(win.births).reshape(-1, 2).sum(axis=0)
    assert births[0] == sum(1 for e in edges
                            if e[0] == dynamics.EDGE_ATTACK)
    assert births[1] == sum(1 for e in edges
                            if e[0] == dynamics.EDGE_RESPAWN)


@pytest.mark.parametrize("layout", ["rowmajor", "popmajor"])
def test_multi_lineage_identity_and_consistency(layout):
    cfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(24, 16), attacking_rate=0.3,
        learn_from_rate=0.2, train=0, remove_divergent=True,
        remove_zero=True, layout=layout)
    st = multisoup.seed_multi(cfg, jax.random.key(0))
    lins = dynamics.seed_lineage_blocks(cfg.sizes)
    plain = multisoup.evolve_multi(cfg, st, generations=4)
    final, (lins2, win, stats) = multisoup.evolve_multi(
        cfg, st, generations=4, lineage=True, lineage_state=lins,
        lineage_capacity=512)
    for a, b in zip(plain.weights, final.weights):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # one shared pid space: globally unique, every carry on the same counter
    pids = np.concatenate([np.asarray(l.pid) for l in lins2])
    assert len(set(pids.tolist())) == cfg.total
    assert len({int(l.next_pid) for l in lins2}) == 1
    # edge recount against the carries: every attack/respawn edge's dst is
    # a minted pid; counts match the exact birth counters
    rows = dynamics.window_edge_rows(win, 512)
    births = np.asarray(win.births).reshape(-1, 2).sum(axis=0)
    n_att = sum(1 for r in rows if r[0] == dynamics.EDGE_ATTACK)
    n_re = sum(1 for r in rows if r[0] == dynamics.EDGE_RESPAWN)
    assert int(np.asarray(win.dropped).sum()) == 0
    assert (births[0], births[1]) == (n_att, n_re)
    assert int(lins2[0].next_pid) == cfg.total + n_att + n_re
    # per-type census covers every particle
    for n_t, s in zip(cfg.sizes, stats):
        assert int(np.asarray(s.census).sum()) == n_t


def test_multi_lineage_numpy_recount_rowmajor():
    """Multisoup recount: replay the global attack draw + per-type learn
    draws and the per-type uid trails; mint bases must chain type-major
    through one shared counter."""
    cfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(12, 8), attacking_rate=0.4,
        learn_from_rate=0.3, train=0, remove_divergent=True,
        remove_zero=True, layout="rowmajor")
    n, gens = cfg.total, 3
    offs = cfg.offsets
    st = multisoup.seed_multi(cfg, jax.random.key(5))
    states = [st]
    for _ in range(gens):
        states.append(multisoup.evolve_multi(cfg, states[-1],
                                             generations=1))

    pid = [np.arange(offs[t], offs[t + 1], dtype=np.int64)
           for t in range(2)]
    next_pid = n
    edges = []
    for t in range(gens):
        s0 = states[t]
        _key, k_ag, k_at, _k_lg, k_lt, _k_re = jax.random.split(s0.key, 6)
        attack_gate = np.asarray(jax.random.uniform(k_ag, (n,))
                                 < cfg.attacking_rate)
        attack_tgt = np.asarray(jax.random.randint(k_at, (n,), 0, n))
        att_idx = np.full(n, -1, np.int64)
        for lane in range(n):
            if attack_gate[lane]:
                att_idx[attack_tgt[lane]] = max(att_idx[attack_tgt[lane]],
                                                lane)
        _k_lg_arr = np.asarray(jax.random.uniform(_k_lg, (n,)))
        all_pid0 = np.concatenate(pid)

        def owner(g):  # pid of a global index
            return all_pid0[g]

        for ty in range(2):
            n_t = cfg.sizes[ty]
            att_b = att_idx[offs[ty]:offs[ty + 1]]
            old = pid[ty].copy()
            for lane in np.nonzero(att_b >= 0)[0]:
                src = owner(att_b[lane])
                pid[ty][lane] = next_pid
                next_pid += 1
                edges.append([dynamics.EDGE_ATTACK, t, src,
                              pid[ty][lane], old[lane]])
            mid = pid[ty].copy()
            learn_gate = _k_lg_arr[offs[ty]:offs[ty + 1]] \
                < cfg.learn_from_rate
            learn_tgt = np.asarray(jax.random.randint(
                jax.random.fold_in(k_lt, ty), (n_t,), 0, n_t))
            for lane in np.nonzero(learn_gate)[0]:
                edges.append([dynamics.EDGE_LEARN, t,
                              mid[learn_tgt[lane]], mid[lane], -1])
            dead = (np.asarray(states[t].uids[ty])
                    != np.asarray(states[t + 1].uids[ty]))
            for lane in np.nonzero(dead)[0]:
                pid[ty][lane] = next_pid
                next_pid += 1
                edges.append([dynamics.EDGE_RESPAWN, t, -1,
                              pid[ty][lane], mid[lane]])

    lins = dynamics.seed_lineage_blocks(cfg.sizes)
    final, (lins2, win, _stats) = multisoup.evolve_multi(
        cfg, st, generations=gens, lineage=True, lineage_state=lins,
        lineage_capacity=1024)
    for ty in range(2):
        np.testing.assert_array_equal(np.asarray(lins2[ty].pid), pid[ty])
    assert int(lins2[0].next_pid) == next_pid
    assert dynamics.window_edge_rows(win, 1024) == edges


# ------------------------------------------------------------- sharded


def test_sharded_lineage_popmajor_bitwise_parity(mesh):
    """Sharded-global ids: unique across shards AND (popmajor) bit-identical
    pids/parents/births/edges/census to the single-device run."""
    from srnn_tpu.parallel import make_sharded_state
    from srnn_tpu.parallel.sharded_soup import sharded_evolve

    cfg = _cfg("popmajor")
    st = make_sharded_state(cfg, mesh, jax.random.key(0))
    lin = dynamics.place_lineage(mesh, dynamics.seed_lineage(cfg.size))
    plain = sharded_evolve(cfg, mesh, st, generations=5)
    final, (lin2, win, fs) = sharded_evolve(
        cfg, mesh, st, generations=5, lineage=True, lineage_state=lin,
        lineage_capacity=64)
    np.testing.assert_array_equal(np.asarray(plain.weights),
                                  np.asarray(final.weights))
    pids = np.asarray(lin2.pid)
    assert len(set(pids.tolist())) == cfg.size

    st1 = soup.seed(cfg, jax.random.key(0))
    f1, (l1, w1, fs1) = _evolve_lineage(cfg, st1, 5, cap=512)
    np.testing.assert_array_equal(np.asarray(l1.pid), pids)
    np.testing.assert_array_equal(np.asarray(l1.parent),
                                  np.asarray(lin2.parent))
    np.testing.assert_array_equal(np.asarray(l1.birth),
                                  np.asarray(lin2.birth))
    assert int(l1.next_pid) == int(lin2.next_pid)
    # per-shard windows concatenate; the edge MULTISET matches exactly
    assert sorted(map(tuple, dynamics.window_edge_rows(win, 64))) == \
        sorted(map(tuple, dynamics.window_edge_rows(w1, 512)))
    np.testing.assert_array_equal(np.asarray(fs.census),
                                  np.asarray(fs1.census))
    np.testing.assert_array_equal(np.asarray(fs.transitions),
                                  np.asarray(fs1.transitions))


def test_sharded_lineage_rowmajor_unique_and_identity(mesh):
    from srnn_tpu.parallel import make_sharded_state
    from srnn_tpu.parallel.sharded_soup import sharded_evolve

    cfg = _cfg("rowmajor")
    st = make_sharded_state(cfg, mesh, jax.random.key(1))
    lin = dynamics.place_lineage(mesh, dynamics.seed_lineage(cfg.size))
    plain = sharded_evolve(cfg, mesh, st, generations=4)
    final, (lin2, win, fs) = sharded_evolve(
        cfg, mesh, st, generations=4, lineage=True, lineage_state=lin,
        lineage_capacity=64)
    np.testing.assert_array_equal(np.asarray(plain.weights),
                                  np.asarray(final.weights))
    assert len(set(np.asarray(lin2.pid).tolist())) == cfg.size
    assert int(np.asarray(fs.census).sum()) == cfg.size


def test_sharded_multi_lineage_parity(mesh):
    from srnn_tpu.parallel import make_sharded_multi_state
    from srnn_tpu.parallel.sharded_multisoup import sharded_evolve_multi

    cfg = multisoup.MultiSoupConfig(
        topos=(WW, AGG), sizes=(24, 16), attacking_rate=0.3,
        learn_from_rate=0.2, train=0, remove_divergent=True,
        remove_zero=True, layout="popmajor")
    st = make_sharded_multi_state(cfg, mesh, jax.random.key(0))
    lins = tuple(dynamics.place_lineage(mesh, l)
                 for l in dynamics.seed_lineage_blocks(cfg.sizes))
    plain = sharded_evolve_multi(cfg, mesh, st, generations=4)
    final, (lins2, win, stats) = sharded_evolve_multi(
        cfg, mesh, st, generations=4, lineage=True, lineage_state=lins,
        lineage_capacity=64)
    for a, b in zip(plain.weights, final.weights):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    pids = np.concatenate([np.asarray(l.pid) for l in lins2])
    assert len(set(pids.tolist())) == cfg.total

    st1 = multisoup.seed_multi(cfg, jax.random.key(0))
    f1, (l1, w1, s1) = multisoup.evolve_multi(
        cfg, st1, generations=4, lineage=True,
        lineage_state=dynamics.seed_lineage_blocks(cfg.sizes),
        lineage_capacity=512)
    for a, b in zip(l1, lins2):
        np.testing.assert_array_equal(np.asarray(a.pid), np.asarray(b.pid))
    assert sorted(map(tuple, dynamics.window_edge_rows(win, 64))) == \
        sorted(map(tuple, dynamics.window_edge_rows(w1, 512)))


# ----------------------------------------------------- capacity overflow


def test_edge_capacity_overflow_drops_and_counts():
    cfg = _cfg("popmajor")
    st = soup.seed(cfg, jax.random.key(0))
    _, (lin_big, win_big, _) = _evolve_lineage(cfg, st, 5, cap=2048)
    _, (lin_small, win_small, _) = _evolve_lineage(cfg, st, 5, cap=8)
    total = int(np.asarray(win_big.n_edges).sum())
    kept = int(np.asarray(win_small.n_edges).sum())
    dropped = int(np.asarray(win_small.dropped).sum())
    assert kept == 8 and dropped == total - kept and dropped > 0
    # the sampled prefix is the full stream's prefix
    assert dynamics.window_edge_rows(win_small, 8) == \
        dynamics.window_edge_rows(win_big, 2048)[:8]
    # births/pids are mask-sums, not buffer reads: exact despite the drops
    np.testing.assert_array_equal(np.asarray(win_small.births),
                                  np.asarray(win_big.births))
    np.testing.assert_array_equal(np.asarray(lin_small.pid),
                                  np.asarray(lin_big.pid))


# -------------------------------------------------- fixpoint census math


def test_fixpoint_census_and_transitions_crafted():
    n, p = 6, WW.num_weights
    w = np.zeros((n, p), np.float32)
    w[0] = 0.0                      # zero basin
    w[1] = np.nan                   # divergent (weights nonfinite)
    w[2] = 3.0                      # drifting (linear ww: f(w) != w)
    w[3] = 1e9                      # drifting but large
    w[4] = 5e-5                     # inside epsilon -> zero basin
    w[5] = 2.0
    stats = soup.probe_dynamics(WW, jnp.asarray(w), 1e-4)
    census = np.asarray(stats.census)
    assert census[dynamics.BASIN_ZERO] == 2
    assert census[dynamics.BASIN_DIV] >= 1
    assert census.sum() == n
    # probe transitions come from the unknown row only
    trans = np.asarray(stats.transitions)
    assert trans[0].sum() == n and trans[1:].sum() == 0

    # close_window folds the carried labels into the transition matrix
    prev = jnp.asarray(np.full(n, dynamics.BASIN_DRIFT, np.int32))
    lin = dynamics.seed_lineage(n)._replace(basin=prev)
    fw = jnp.asarray(w)  # pretend f(w) == w: every finite particle "fixed"
    lin2, s2 = dynamics.close_window(lin, jnp.asarray(w), fw, -1, 1e-4)
    t2 = np.asarray(s2.transitions)
    # every particle transitions FROM the drifting row (prev labels)
    assert t2[1 + dynamics.BASIN_DRIFT].sum() == n and t2[0].sum() == 0
    # zero-basin precedence beats the fixpoint label (reference class order)
    c2 = np.asarray(s2.census)
    assert c2[dynamics.BASIN_ZERO] == 2
    assert c2[dynamics.BASIN_FIX] == n - 2 - c2[dynamics.BASIN_DIV]
    # the new labels were stored for the NEXT window's transitions
    np.testing.assert_array_equal(
        np.asarray(dynamics.close_window(lin2, jnp.asarray(w), fw, -1,
                                         1e-4)[1].transitions)[0].sum(), 0)


def test_census_matches_numpy_recount_after_run():
    from srnn_tpu.nets import apply_to_weights

    cfg = _cfg("popmajor")
    st = soup.seed(cfg, jax.random.key(2))
    final, (lin, win, stats) = _evolve_lineage(cfg, st, 4)
    w = np.asarray(final.weights)
    fw = np.asarray(jax.vmap(
        lambda wi: apply_to_weights(cfg.topo, wi, wi))(final.weights))
    linf = np.max(np.abs(fw - w), axis=-1)
    div = ~np.isfinite(w).all(axis=-1) | ~np.isfinite(linf)
    zero = (np.abs(w) <= cfg.epsilon).all(axis=-1) & ~div
    fix = ~div & ~zero & (linf < cfg.epsilon)
    drift = ~(div | zero | fix)
    expect = [fix.sum(), drift.sum(), div.sum(), zero.sum()]
    np.testing.assert_array_equal(np.asarray(stats.census), expect)
    np.testing.assert_array_equal(np.asarray(lin.basin),
                                  np.select([div, zero, fix],
                                            [dynamics.BASIN_DIV,
                                             dynamics.BASIN_ZERO,
                                             dynamics.BASIN_FIX],
                                            dynamics.BASIN_DRIFT))


# ------------------------------------------------------- host round-trip


def test_genealogy_roundtrip_writer_forest_report(tmp_path, capsys):
    cfg = _cfg("popmajor")
    st = soup.seed(cfg, jax.random.key(0))
    run_dir = str(tmp_path)
    writer = dynamics.LineageWriter(run_dir, n=cfg.size, capacity=512,
                                    epsilon=cfg.epsilon)
    lin = dynamics.seed_lineage(cfg.size)
    gen = 0
    for _ in range(3):
        st, (lin, win, stats) = soup.evolve(
            cfg, st, generations=4, lineage=True, lineage_state=lin,
            lineage_capacity=512)
        row = dynamics.window_record(gen, gen + 4, win, stats, 512,
                                     next_pid=int(lin.next_pid))
        writer.append(row)
        gen += 4
    writer.close()

    epochs = genealogy.load_lineage(run_dir)
    assert len(epochs) == 1 and len(epochs[0]["windows"]) == 3
    forest = genealogy.build_forest(epochs[0])
    assert forest.dropped == 0
    # forest state agrees with the device carry: live pids == current pids
    assert sorted(forest.alive) == sorted(np.asarray(lin.pid).tolist())
    for lane, p in enumerate(np.asarray(lin.pid).tolist()):
        assert forest.birth[p] == int(np.asarray(lin.birth)[lane])
        assert forest.parent[p] == int(np.asarray(lin.parent)[lane])
    assert len(forest.parent) == int(lin.next_pid)
    rows = genealogy.dominant_lineages(forest)
    assert rows and sum(r["alive"] for r in
                        genealogy.dominant_lineages(forest, top=10**9)) \
        == cfg.size
    surv = genealogy.survival_stats(forest)
    assert surv["terminated"] == int(lin.next_pid) - cfg.size
    traj = genealogy.census_trajectory(epochs[0]["windows"])
    assert [r["gen"] for r in traj] == [4, 8, 12]

    # the CLI renders the dominant-lineage table + census trajectory
    assert report.main(["--dynamics", run_dir]) == 0
    out = capsys.readouterr().out
    assert "dominant lineages" in out
    assert "fixpoint census trajectory" in out
    assert report.main(["--dynamics", run_dir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["minted"] == int(lin.next_pid)


def test_lineage_state_sidecar_roundtrip(tmp_path):
    lin = dynamics.seed_lineage(16)
    dynamics.save_lineage_state(str(tmp_path), lin, gen=7)
    got = dynamics.load_lineage_state(str(tmp_path), 7)
    assert got is not None and hasattr(got, "next_pid")  # one LineageState
    np.testing.assert_array_equal(np.asarray(got.pid), np.asarray(lin.pid))
    assert dynamics.load_lineage_state(str(tmp_path), 8) is None
    lins = dynamics.seed_lineage_blocks((8, 8))
    dynamics.save_lineage_state(str(tmp_path), lins, gen=3)
    got = dynamics.load_lineage_state(str(tmp_path), 3)
    assert not hasattr(got, "next_pid") and len(got) == 2  # per-type tuple
    np.testing.assert_array_equal(np.asarray(got[1].pid),
                                  np.asarray(lins[1].pid))


def test_dynamics_registry_metric_names(tmp_path):
    from srnn_tpu.telemetry.metrics import MetricsRegistry
    from srnn_tpu.telemetry.names import CANONICAL_METRICS

    cfg = _cfg("popmajor")
    st = soup.seed(cfg, jax.random.key(0))
    _, (lin, win, stats) = _evolve_lineage(cfg, st, 3)
    row = dynamics.window_record(0, 3, win, stats, 512,
                                 next_pid=int(lin.next_pid))
    reg = MetricsRegistry()
    dynamics.update_dynamics_registry(reg, row)
    prom = str(tmp_path / "dyn_test.prom")
    reg.write_textfile(prom)
    with open(prom) as f:
        text = f.read()
    assert "srnn_soup_dynamics_windows_total" in text
    assert "srnn_soup_dynamics_basin_particles" in text
    for name in ("soup_dynamics_edges_total", "soup_dynamics_births_total",
                 "soup_dynamics_next_pid"):
        assert name in CANONICAL_METRICS and name in text


# -------------------------------------------------------------- e2e mega


def test_mega_soup_lineage_e2e_report_and_resume(tmp_path, capsys):
    """The acceptance scenario: a real (smoke-scale) mega_soup run with
    --lineage writes the lineage.jsonl stream, `report --dynamics` renders
    the dominant-lineage table + fixpoint census from it, and a resumed
    run CONTINUES the pid epoch from the sidecar."""
    from srnn_tpu.setups import REGISTRY

    d = REGISTRY["mega_soup"](["--smoke", "--lineage",
                               "--root", str(tmp_path / "run")])
    path = os.path.join(d, "lineage.jsonl")
    assert os.path.exists(path)
    epochs = genealogy.load_lineage(d)
    assert len(epochs) == 1
    assert len(epochs[0]["windows"]) == 3          # 6 gens / 2-gen chunks
    assert epochs[0]["header"]["n"] == 64
    assert os.path.exists(os.path.join(d, "lineage_state.npz"))
    # dynamics metrics reached the prom sink
    prom = open(os.path.join(d, "metrics.prom")).read()
    assert "srnn_soup_dynamics_windows_total 3" in prom

    assert report.main(["--dynamics", d]) == 0
    out = capsys.readouterr().out
    assert "dominant lineages" in out and "fixpoint census" in out

    # resume: two more generations continue the same epoch and pid space
    # (--lineage is an observability knob like --no-health: CLI-controlled,
    # not persisted in config.json — pass it again on resume)
    d2 = REGISTRY["mega_soup"](["--smoke", "--generations", "8",
                                "--lineage", "--resume", d])
    assert d2 == d
    epochs = genealogy.load_lineage(d)
    assert len(epochs) == 1, "restored carry must continue the epoch"
    assert len(epochs[0]["windows"]) == 4
    forest = genealogy.build_forest(epochs[0])
    assert len(forest.alive) == 64


def test_mega_multisoup_lineage_e2e(tmp_path):
    from srnn_tpu.setups import REGISTRY

    d = REGISTRY["mega_multisoup"](["--smoke", "--lineage",
                                    "--root", str(tmp_path / "run")])
    epochs = genealogy.load_lineage(d)
    [epoch] = epochs
    assert epoch["header"]["type_names"] == ["weightwise", "aggregating",
                                             "recurrent"]
    w = epoch["windows"][-1]
    assert set(w["fixpoints_by_type"]) == {"weightwise", "aggregating",
                                           "recurrent"}
    total = sum(sum(doc["census"].values())
                for doc in w["fixpoints_by_type"].values())
    assert total == 48
