"""Cross-architecture application + heterogeneous soups."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology, apply_to_weights, init_flat
from srnn_tpu.fixtures import identity_fixpoint_flat
from srnn_tpu.multisoup import (MultiSoupConfig, count_multi, evolve_multi,
                                evolve_multi_step, seed_multi)
from srnn_tpu.nets.cross import cross_apply

TOPOS = {
    "weightwise": Topology("weightwise", width=2, depth=2),
    "aggregating": Topology("aggregating", width=2, depth=2, aggregates=4),
    "fft": Topology("fft", width=2, depth=2, aggregates=4),
    "recurrent": Topology("recurrent", width=2, depth=2),
}


@pytest.mark.parametrize("variant", sorted(TOPOS))
def test_cross_apply_reduces_to_apply_same_topo(variant):
    """cross_apply(t, a, t, v) == apply_to_weights(t, a, v) bit-for-bit
    (for the aggregating falsy-max quirk variant this only holds for the
    default 'average' aggregator, which all experiments use)."""
    topo = TOPOS[variant]
    a = init_flat(topo, jax.random.key(0)) * 0.5
    v = init_flat(topo, jax.random.key(1)) * 0.5
    np.testing.assert_array_equal(
        np.asarray(cross_apply(topo, a, topo, v)),
        np.asarray(apply_to_weights(topo, a, v)))


@pytest.mark.parametrize("att,vic", list(itertools.product(sorted(TOPOS), repeat=2)))
def test_cross_apply_shapes(att, vic):
    """Any attacker variant produces a victim-shaped finite output at tame
    weight scales."""
    ta, tv = TOPOS[att], TOPOS[vic]
    a = init_flat(ta, jax.random.key(2)) * 0.3
    v = init_flat(tv, jax.random.key(3)) * 0.3
    out = cross_apply(ta, a, tv, v)
    assert out.shape == (tv.num_weights,)
    assert np.isfinite(np.asarray(out)).all()


def test_ww_identity_attacker_reproduces_any_victim():
    """The weightwise identity fixpoint computes f([w, ids]) = w, so as an
    attacker it must reproduce ANY victim's weights exactly — including a
    victim of a different architecture."""
    ww = TOPOS["weightwise"]
    ident = identity_fixpoint_flat(ww)
    for vic in ("aggregating", "recurrent", "fft"):
        tv = TOPOS[vic]
        v = init_flat(tv, jax.random.key(4))
        out = cross_apply(ww, ident, tv, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(v), atol=1e-6)


def test_multisoup_generation_and_conservation():
    cfg = MultiSoupConfig(
        topos=(TOPOS["weightwise"], TOPOS["aggregating"], TOPOS["recurrent"]),
        sizes=(6, 5, 4), attacking_rate=0.5, learn_from_rate=0.3,
        learn_from_severity=1, train=1,
        remove_divergent=True, remove_zero=True)
    state = seed_multi(cfg, jax.random.key(0))
    assert int(state.next_uid) == 15
    new_state, events = evolve_multi_step(cfg, state)
    assert int(new_state.time) == 1
    counts = np.asarray(count_multi(cfg, new_state))
    assert counts.shape == (3, 5)
    assert counts.sum(axis=1).tolist() == [6, 5, 4]  # per-type conservation
    # uids stay globally unique across types after respawns
    all_uids = np.concatenate([np.asarray(u) for u in new_state.uids])
    assert len(set(all_uids.tolist())) == 15


def test_multisoup_deterministic_and_evolves():
    cfg = MultiSoupConfig(
        topos=(TOPOS["weightwise"], TOPOS["aggregating"]),
        sizes=(5, 5), attacking_rate=0.4, learn_from_rate=0.0, train=0,
        remove_divergent=True, remove_zero=True)
    a = evolve_multi(cfg, seed_multi(cfg, jax.random.key(9)), generations=5)
    b = evolve_multi(cfg, seed_multi(cfg, jax.random.key(9)), generations=5)
    for wa, wb in zip(a.weights, b.weights):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    assert int(a.time) == 5


def test_multisoup_cross_attack_actually_crosses():
    """With one guaranteed weightwise attacker (identity net) and an
    always-attack rate, the aggregating victims' weights must change to the
    identity transform of themselves (i.e. be reproduced exactly) when hit
    by the WW identity attacker — proving the cross-type path executes."""
    ww, agg = TOPOS["weightwise"], TOPOS["aggregating"]
    cfg = MultiSoupConfig(topos=(ww, agg), sizes=(1, 3), attacking_rate=1.0,
                          learn_from_rate=0.0, train=0)
    state = seed_multi(cfg, jax.random.key(1))
    # plant the identity fixpoint as the sole WW particle
    state = state._replace(weights=(
        identity_fixpoint_flat(ww)[None, :], state.weights[1]))
    new_state, events = evolve_multi_step(cfg, state)
    # actions recorded for the attackers that fired
    acts = np.concatenate([np.asarray(a) for a in events.action])
    assert (acts == 2).any()  # ACT_ATTACK somewhere
    # any aggregating victim attacked by the WW identity keeps its weights
    # (identity reproduces the victim); victims attacked by aggregating
    # particles get aggregate-replicated rows instead — check at least the
    # shapes/finiteness and that the step ran the cross path without error
    assert np.isfinite(np.asarray(new_state.weights[1])).all()


def test_multisoup_popmajor_matches_rowmajor():
    """The lane-major mixed soup (layout='popmajor',
    ops/popmajor_cross.py) must track the row-major path under the shared
    PRNG stream: full dynamics with all four variants, cross-type attacks
    included, single step and the multi-generation carry."""
    cfg_row = MultiSoupConfig(
        topos=(TOPOS["weightwise"], TOPOS["aggregating"], TOPOS["fft"],
               TOPOS["recurrent"]),
        sizes=(6, 5, 4, 5), attacking_rate=0.5, learn_from_rate=0.3,
        learn_from_severity=2, train=2,
        remove_divergent=True, remove_zero=True)
    cfg_pop = cfg_row._replace(layout="popmajor")
    st = seed_multi(cfg_row, jax.random.key(3))
    row_s, row_ev = evolve_multi_step(cfg_row, st)
    pop_s, pop_ev = evolve_multi_step(cfg_pop, st)
    for t in range(4):
        np.testing.assert_array_equal(np.asarray(row_ev.action[t]),
                                      np.asarray(pop_ev.action[t]))
        np.testing.assert_array_equal(np.asarray(row_s.uids[t]),
                                      np.asarray(pop_s.uids[t]))
        np.testing.assert_allclose(np.asarray(row_s.weights[t]),
                                   np.asarray(pop_s.weights[t]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(row_ev.loss[t]),
                                   np.asarray(pop_ev.loss[t]),
                                   rtol=1e-3, atol=1e-6)
    row = evolve_multi(cfg_row, st, generations=6)
    pop = evolve_multi(cfg_pop, st, generations=6)
    assert int(pop.time) == 6
    for t in range(4):
        np.testing.assert_array_equal(np.asarray(row.uids[t]),
                                      np.asarray(pop.uids[t]))
        np.testing.assert_allclose(np.asarray(row.weights[t]),
                                   np.asarray(pop.weights[t]),
                                   rtol=1e-3, atol=1e-5)


def test_multisoup_popmajor_rejects_random_shuffler():
    shuf = Topology("aggregating", width=2, depth=2, shuffler="random")
    cfg = MultiSoupConfig(topos=(TOPOS["weightwise"], shuf), sizes=(2, 2),
                          layout="popmajor")
    base = MultiSoupConfig(topos=(TOPOS["weightwise"], shuf), sizes=(2, 2))
    with pytest.raises(ValueError):
        evolve_multi_step(cfg, seed_multi(base, jax.random.key(0)))
