"""Static observability gate: runtime output must route through
``Experiment.log`` / the telemetry sinks, never bare ``print()``.

Walks the ``srnn_tpu/`` package AST and fails on any ``print(...)`` call
that (a) lives outside the sanctioned modules — the reference
``PrintingObject`` shim, ``experiment.py`` (whose ``log``/``__enter__``
ARE the human stdout channel), and the CLI entry points — and (b) does
not explicitly route via a ``file=`` keyword (diagnostics deliberately
sent to stderr, e.g. backend-init retries, stay legal everywhere).
"""

import ast
import os

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "srnn_tpu")

#: modules whose stdout prints ARE their contract (relative to srnn_tpu/)
ALLOWED_FILES = {
    "utils/printing.py",     # the reference PrintingObject parity shim
    "experiment.py",         # Experiment.log is the human stdout channel
    "precompile.py",         # CLI: prints its one JSON result line
    "viz.py",                # CLI: run-dir walker output
    "telemetry/report.py",   # CLI: renders the telemetry summary
}
#: CLI entry-point trees (every setup is a __main__-dispatched script)
ALLOWED_DIRS = ("setups/",)


def _stray_prints(path: str, rel: str):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=rel)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            continue
        if any(kw.arg == "file" for kw in node.keywords):
            continue  # explicitly routed (stderr diagnostics)
        yield f"{rel}:{node.lineno}"


def test_no_stray_prints():
    offenders = []
    for root, _dirs, files in os.walk(PKG):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, PKG).replace(os.sep, "/")
            if rel in ALLOWED_FILES or rel.startswith(ALLOWED_DIRS):
                continue
            offenders.extend(_stray_prints(path, rel))
    assert not offenders, (
        "bare print() outside the sanctioned output channels — route "
        "through Experiment.log / telemetry sinks, or print(..., "
        f"file=sys.stderr) for diagnostics: {offenders}")
