"""Thin wrapper: the stray-print gate now lives in the srnnlint
framework (``srnn_tpu/analysis/passes/prints.py``).  This file keeps the
historical CI entry point; the walker itself is shared with the CLI
(``python -m srnn_tpu.analysis stray-prints``)."""

import os

from srnn_tpu.analysis import AnalysisContext, run_analysis, select

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_no_stray_prints():
    ctx = AnalysisContext.from_root(REPO_ROOT)
    result = run_analysis(ctx, select(["stray-prints"]))
    assert not result.errors, "\n".join(f.render() for f in result.errors)
