"""Visualization: every renderer produces an image; the walker finds and
renders artifacts exactly once."""

import os

import numpy as np
import pytest

from srnn_tpu import viz
from srnn_tpu.setups import REGISTRY


@pytest.fixture(scope="module")
def traj_artifact():
    rng = np.random.default_rng(0)
    t, n, p = 12, 5, 14
    w = rng.normal(size=(t, n, p)).astype(np.float32).cumsum(axis=0)
    return {"weights": w}


def test_particle_trajectories_trial_columns(traj_artifact):
    trajs = viz.particle_trajectories(traj_artifact)
    assert len(trajs) == 5
    assert trajs[0]["trajectory"].shape == (12, 14)
    assert trajs[3]["uid"] == 3


def test_particle_trajectories_split_on_respawn():
    w = np.zeros((6, 2, 3), np.float32)
    uids = np.array([[0, 1]] * 3 + [[5, 1]] * 3)  # particle 0 respawns at t=3
    trajs = viz.particle_trajectories({"weights": w, "uids": uids})
    assert len(trajs) == 3
    assert sorted(t["uid"] for t in trajs) == [0, 1, 5]
    lifetimes = sorted(len(t["trajectory"]) for t in trajs)
    assert lifetimes == [3, 3, 6]


def test_particle_trajectories_drops_nonfinite():
    w = np.ones((4, 1, 3), np.float32)
    w[2] = np.nan
    trajs = viz.particle_trajectories({"weights": w})
    assert len(trajs) == 1 and len(trajs[0]["trajectory"]) == 3


def test_3d_and_tsne_plots(traj_artifact, tmp_path):
    out = viz.plot_latent_trajectories_3d(traj_artifact, str(tmp_path / "t3.png"))
    assert os.path.getsize(out) > 5000
    out = viz.plot_latent_trajectories(traj_artifact, str(tmp_path / "t2.png"))
    assert os.path.getsize(out) > 5000


def test_line_bar_box(tmp_path):
    data = [{"xs": [0, 10, 20], "ys": [0.1, 0.5, 0.9], "zs": [0, 0.2, 0.4]}]
    out = viz.line_plot(data, ["ww"], str(tmp_path / "line.png"))
    assert os.path.getsize(out) > 5000
    out = viz.plot_bars(np.array([[3, 4, 2, 0, 1], [1, 1, 1, 1, 6]]),
                        ["a", "b"], str(tmp_path / "bars.png"))
    assert os.path.getsize(out) > 5000
    xs = np.repeat([1.0, 0.1], 8)
    box = {"xs": xs, "ys": np.arange(16), "zs": np.arange(16)[::-1]}
    out = viz.plot_box(box, str(tmp_path / "box.png"))
    assert os.path.getsize(out) > 5000


def test_html_trajectories(traj_artifact, tmp_path):
    """The interactive HTML view is self-contained: embedded data, inline
    renderer, no external resources (parity with the reference's offline
    plotly HTML, visualization.py:119-179)."""
    from srnn_tpu.viz_html import write_html_trajectories_3d

    out = write_html_trajectories_3d(traj_artifact, str(tmp_path / "t3.html"))
    html = open(out).read()
    assert html.startswith("<!DOCTYPE html>")
    assert '"xyz":' in html and "canvas" in html
    assert "http://" not in html and "https://" not in html  # offline
    assert html.count('"color"') == 5  # one series per particle


def test_search_and_apply_end_to_end(tmp_path):
    """Run two smoke setups, then the walker renders their artifacts and is
    idempotent on the second pass (visualization.py:255-275 semantics)."""
    REGISTRY["soup_trajectorys"](["--smoke", "--root", str(tmp_path)])
    REGISTRY["mixed_soup"](["--smoke", "--root", str(tmp_path)])
    outs = viz.search_and_apply(str(tmp_path))
    produced = {os.path.basename(o) for o in outs}
    assert "soup_trajectories_3d.png" in produced
    assert "soup_trajectories_3d.html" in produced  # interactive twin
    assert "sweep.png" in produced
    assert "counters.png" in produced  # soup_trajectorys saves all_counters
    again = viz.search_and_apply(str(tmp_path))
    assert again == []
    # a run dir with the PNG but no HTML twin (pre-HTML render, partial
    # failure) is revisited and backfilled, not skipped
    html = next(p for p in outs if p.endswith("soup_trajectories_3d.html"))
    os.remove(html)
    backfilled = viz.search_and_apply(str(tmp_path))
    assert html in backfilled and os.path.exists(html)


def test_cli(tmp_path, capsys):
    REGISTRY["known_fixpoint_variation"](
        ["--root", str(tmp_path), "--depth", "2", "--trials", "4",
         "--max-steps", "5"])
    assert viz.main(["-i", str(tmp_path)]) == 0
    assert "variation_box.png" in capsys.readouterr().out


def test_plot_histogram_and_bands(tmp_path):
    """Generic plotters (visualization.py:183-252 parity)."""
    out = viz.plot_histogram(
        [{"name": np.array(["a", "b", "a"])}, {"name": np.array(["b", "b"])}],
        str(tmp_path / "hist.png"), title="hist")
    assert os.path.exists(out)
    x = np.arange(5)
    out = viz.line_plot_with_bands(
        [{"x": x, "main_y": x * 1.0, "upper_y": x + 1.0, "lower_y": x - 1.0,
          "name": "s0"},
         {"x": x, "main_y": x * 0.5, "upper_y": x * 0.5 + 0.2,
          "lower_y": x * 0.5 - 0.2}],
        str(tmp_path / "bands.png"))
    assert os.path.exists(out)


def test_mega_curve_rendered_by_walker(tmp_path):
    """The walker renders a class-count-vs-generation curve for mega_soup
    run dirs (marked by config.json; counts live in events.jsonl)."""
    d = REGISTRY["mega_soup"](["--smoke", "--root", str(tmp_path)])
    outs = viz.search_and_apply(str(tmp_path))
    assert os.path.join(d, "mega_curve.png") in outs
    assert viz.search_and_apply(str(tmp_path)) == []  # idempotent


def test_particle_trajectories_subsampling_cap():
    """Mega-scale artifacts render a deterministic strided subset; small
    artifacts keep every column; the stride includes both ends."""
    from srnn_tpu.viz import particle_trajectories

    t_len, n, p = 3, 1000, 4
    art = {"weights": np.random.default_rng(0).normal(size=(t_len, n, p))}
    full = particle_trajectories(art)
    assert len(full) == n
    capped = particle_trajectories(art, max_particles=64)
    assert len(capped) == 64
    uids = [t["uid"] for t in capped]
    assert uids[0] == 0 and uids[-1] == n - 1
    again = particle_trajectories(art, max_particles=64)
    assert [t["uid"] for t in again] == uids  # deterministic stride
