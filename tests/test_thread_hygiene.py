"""Static thread-hygiene gate: every thread started under ``srnn_tpu/``
must go through ``utils.pipeline.spawn_thread`` — the package's thread
factory — so it is (a) registered with the join-on-exit registry that the
shutdown tests audit (``pipeline.live_threads()``) and (b) non-daemon
unless explicitly opted out, so interpreter exit can never strand
buffered I/O (a daemon writer dying mid-fsync is a silent data-loss
path).

Walks the package AST and fails on any direct ``threading.Thread(...)``
/ ``Thread(...)`` construction outside ``utils/pipeline.py`` itself (the
factory's own call site), and on any ``spawn_thread(..., daemon=True)``
whose literal True sneaks a daemon in without the factory's audit trail —
daemon-ness must be a reviewed, named decision at the factory.
"""

import ast
import os

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "srnn_tpu")

#: the factory's own home — the one sanctioned Thread() call site
FACTORY_FILE = "utils/pipeline.py"

#: reviewed daemon-thread call sites (file -> justification), ONE per
#: file — a second daemon call in a whitelisted file still fails the
#: gate, so the BackgroundWriter (buffered I/O, same file as the
#: ChunkDriver) can never silently go daemon.  Both sites are
#: deliberately NOT joinable: they exist to escape/observe a thread that
#: is presumed wedged below Python, own no buffered I/O, and a non-daemon
#: spelling would hang interpreter exit on the very wedge they watch for.
DAEMON_WHITELIST = {
    "utils/pipeline.py":
        "ChunkDriver stall deadline: the watched finisher thread IS the "
        "presumed-wedged thread",
    "telemetry/flightrec.py":
        "StallSentinel dead-man's switch: fires while the main thread "
        "hangs in a dead backend call",
}


def _is_thread_ctor(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return True  # threading.Thread(...), x.Thread(...)
    return isinstance(f, ast.Name) and f.id == "Thread"


def _offenders(path: str, rel: str):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=rel)
    daemon_sites = 0
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_thread_ctor(node) and rel != FACTORY_FILE:
            yield (f"{rel}:{node.lineno}: direct Thread() — use "
                   "utils.pipeline.spawn_thread (join-on-exit registry)")
        if (isinstance(node.func, (ast.Name, ast.Attribute))
                and (getattr(node.func, "id", None) == "spawn_thread"
                     or getattr(node.func, "attr", None) == "spawn_thread")):
            for kw in node.keywords:
                if (kw.arg == "daemon"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True):
                    daemon_sites += 1
                    if rel not in DAEMON_WHITELIST:
                        yield (f"{rel}:{node.lineno}: "
                               "spawn_thread(daemon=True) — daemon threads "
                               "can strand buffered I/O at interpreter "
                               "exit; justify and whitelist here if truly "
                               "needed")
                    elif daemon_sites > 1:
                        yield (f"{rel}:{node.lineno}: second "
                               "spawn_thread(daemon=True) in a whitelisted "
                               "file — the whitelist covers ONE reviewed "
                               "site per file; review this one separately")


def test_no_unregistered_threads():
    offenders = []
    for root, _dirs, files in os.walk(PKG):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, PKG).replace(os.sep, "/")
            offenders.extend(_offenders(path, rel))
    assert not offenders, "\n".join(offenders)


def test_factory_registers_and_joins():
    """The factory's runtime half of the invariant: spawn_thread lands in
    live_threads() while running and leaves it once joined."""
    import threading

    from srnn_tpu.utils.pipeline import live_threads, spawn_thread

    gate = threading.Event()
    t = spawn_thread(gate.wait, name="hygiene-probe")
    assert t in live_threads() and not t.daemon
    gate.set()
    t.join(5.0)
    assert t not in live_threads()
