"""Thin wrapper: the thread-hygiene gate (direct ``Thread()`` ban, daemon
whitelist + max-one-per-file rule) now lives in the srnnlint framework
(``srnn_tpu/analysis/passes/threads.py``).  The factory's RUNTIME half of
the invariant stays here — static analysis cannot watch a thread join."""

import os

from srnn_tpu.analysis import AnalysisContext, run_analysis, select

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_no_unregistered_threads():
    ctx = AnalysisContext.from_root(REPO_ROOT)
    result = run_analysis(ctx, select(["thread-hygiene"]))
    assert not result.errors, "\n".join(f.render() for f in result.errors)


def test_factory_registers_and_joins():
    """The factory's runtime half of the invariant: spawn_thread lands in
    live_threads() while running and leaves it once joined."""
    import threading

    from srnn_tpu.utils.pipeline import live_threads, spawn_thread

    gate = threading.Event()
    t = spawn_thread(gate.wait, name="hygiene-probe")
    assert t in live_threads() and not t.daemon
    gate.set()
    t.join(5.0)
    assert t not in live_threads()
