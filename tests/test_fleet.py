"""The fleet observatory (PR 12): structured spans, the cross-process
timeline merge + straggler attribution, the live watch console, and the
serve ticket-span breakdown.

The load-bearing contract drilled here: observability NEVER perturbs
results — a run with spans is bitwise-identical to the same run with
``--no-spans`` (the spans are host-only rows), and every reader layer
(merge, report, watch) is a pure file consumer that tolerates torn
files from killed or still-writing processes.
"""

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from srnn_tpu.distributed.hostio import WorkerLog, fetch_tree, set_span_sink
from srnn_tpu.experiment import restore_checkpoint
from srnn_tpu.setups import REGISTRY
from srnn_tpu.telemetry import fleet, watch
from srnn_tpu.telemetry.metrics import MetricsRegistry
from srnn_tpu.telemetry.report import summarize
from srnn_tpu.telemetry.tracing import SpanStream
from srnn_tpu.utils.pipeline import BackgroundWriter

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# structured spans
# ---------------------------------------------------------------------------


def test_span_stream_round_trip_through_writer(tmp_path):
    """Spans ride the BackgroundWriter into a real event file and come
    back with ids/parent/clock intact — through the same WorkerLog
    channel a distributed worker uses."""
    with BackgroundWriter(name="test-span-io") as writer:
        with WorkerLog(str(tmp_path), 1) as log:
            stream = SpanStream(log, trace_id="run-x", process=1,
                                writer=writer)
            root = stream.emit("chunk", 1.0, 0.5, generation=100)
            child = stream.emit("chunk.host_io", 1.1, 0.2, parent=root)
            assert child == root + 1  # monotone ids
            writer.flush()
    rows = [json.loads(l) for l in
            open(tmp_path / "events-p1.jsonl")]
    assert [r["kind"] for r in rows] == ["span", "span"]
    r0, r1 = rows
    assert r0["span"] == "chunk" and r0["trace_id"] == "run-x"
    assert r0["span_id"] == root and "parent" not in r0
    assert r0["start_s"] == 1.0 and r0["seconds"] == 0.5
    assert r0["generation"] == 100 and r0["process"] == 1
    assert r1["parent"] == root


def test_span_stream_timed_and_registry(tmp_path):
    class Events:
        rows = []

        def event(self, **kw):
            self.rows.append(kw)

    reg = MetricsRegistry()
    stream = SpanStream(Events(), trace_id="t", registry=reg)
    with stream.timed("gather", collectives=3) as extra:
        extra["note"] = "ok"
    (row,) = Events.rows
    assert row["span"] == "gather" and row["collectives"] == 3
    assert row["note"] == "ok" and row["seconds"] >= 0
    assert reg.histogram("span_seconds").count(span="gather") == 1


def test_hostio_span_sink_times_fetch_tree():
    """The collective span sink: fetch_tree emits one structured row per
    call while installed, and clearing it makes emission free again."""
    got = []
    set_span_sink(lambda name, dur, **kw: got.append((name, dur, kw)))
    try:
        out = fetch_tree({"a": np.arange(3)})
    finally:
        set_span_sink(None)
    np.testing.assert_array_equal(out["a"], np.arange(3))
    (name, dur, kw), = got
    assert name == "hostio.fetch_tree" and dur >= 0
    assert kw == {"collectives": 0}  # single-process: local resolve only
    got.clear()
    fetch_tree({"a": np.arange(3)})
    assert not got


# ---------------------------------------------------------------------------
# timeline merge + straggler attribution
# ---------------------------------------------------------------------------


def _craft_run_dir(tmp_path):
    """A 3-process run dir: p0 events (with heartbeats + a metrics row),
    p1 out-of-order heartbeats, p2 TRUNCATED mid-row (a killed worker)."""
    run = tmp_path / "run"
    run.mkdir()

    def hb(t, gen, rate, stage):
        return {"t": t, "kind": "heartbeat", "stage": stage,
                "generation": gen, "total_generations": 8,
                "gens_per_sec": rate}

    with open(run / "events.jsonl", "w") as f:
        for row in (hb(1.0, 2, 4.0, "mega_soup@p0/3"),
                    hb(2.0, 4, 4.0, "mega_soup@p0/3"),
                    {"t": 2.1, "kind": "metrics",
                     "metrics": {"srnn_soup_health_nan_frac": 0.0}},
                    {"t": 2.2, "kind": "span", "span": "mega_soup.chunk",
                     "span_id": 1, "trace_id": "r", "start_s": 1.0,
                     "seconds": 1.0}):
            f.write(json.dumps(row) + "\n")
    with open(run / "events-p1.jsonl", "w") as f:
        # out of order on purpose: the merge must sort, not trust file order
        f.write(json.dumps(dict(hb(1.9, 4, 2.0, "mega_soup@p1/3"),
                                process=1)) + "\n")
        f.write(json.dumps(dict(hb(0.9, 2, 2.0, "mega_soup@p1/3"),
                                process=1)) + "\n")
    with open(run / "events-p2.jsonl", "w") as f:
        f.write(json.dumps(dict(hb(1.1, 2, 3.0, "mega_soup@p2/3"),
                                process=2)) + "\n")
        f.write('{"t": 1.8, "kind": "heartbeat", "generation": 4, "trunc')
    (run / "ckpt-gen00000004").mkdir()
    return run


def test_merged_timeline_orders_and_skips_torn(tmp_path):
    run = _craft_run_dir(tmp_path)
    rows, skipped = fleet.merged_timeline(str(run))
    assert skipped == 1  # p2's torn tail dropped, not fatal
    ts = [r["t"] for r in rows]
    assert ts == sorted(ts)
    assert [r["process"] for r in rows if r["kind"] == "heartbeat"] == \
        [1, 0, 2, 1, 0]


def test_fleet_summary_lanes_and_straggler_vs_numpy(tmp_path):
    run = _craft_run_dir(tmp_path)
    s = fleet.fleet_summary(str(run))
    assert set(s["processes"]) == {"0", "1", "2"}
    assert s["worker_files"] == ["events-p1.jsonl", "events-p2.jsonl"]
    assert s["processes"]["0"]["generation"] == 4
    assert s["processes"]["0"]["stage"] == "mega_soup@p0/3"
    assert s["processes"]["2"]["beats"] == 1
    assert s["latest_checkpoint"] == "ckpt-gen00000004"
    # straggler math against a NumPy recount of the crafted rates
    rates = {0: np.median([4.0, 4.0]), 1: np.median([2.0, 2.0]),
             2: np.median([3.0])}
    att = s["straggler"]
    slow = min(rates, key=rates.get)
    assert att["straggler_process"] == slow == 1
    assert att["fastest_process"] == 0
    assert att["skew_ratio"] == pytest.approx(
        max(rates.values()) / min(rates.values()))
    # lag: leader at gen 4, straggler p1 last reported gen 4 -> 0; p2
    # whose parsed rows stop at gen 2 would trail by 2 if slowest
    assert att["lag_generations"] == 4 - 4
    assert att["gens_per_sec"] == {0: 4.0, 1: 2.0, 2: 3.0}


def test_straggler_attribution_edge_cases():
    assert fleet.straggler_attribution({}, {}) is None
    att = fleet.straggler_attribution({0: 5.0}, {0: 7})
    assert att["skew_ratio"] == 1.0 and att["lag_generations"] == 0
    att = fleet.straggler_attribution({0: 5.0, 1: 2.5}, {0: 8, 1: 6})
    assert (att["straggler_process"], att["skew_ratio"],
            att["lag_generations"]) == (1, 2.0, 2)


def test_straggler_gauges_and_live_attribution(tmp_path):
    run = _craft_run_dir(tmp_path)
    att = fleet.live_attribution(str(run), 3)
    # live attribution takes the LAST heartbeat per process
    assert att["gens_per_sec"] == {0: 4.0, 1: 2.0, 2: 3.0}
    assert att["straggler_process"] == 1
    reg = MetricsRegistry()
    fleet.update_straggler_gauges(reg, att)
    rows = reg.rows()
    assert rows["srnn_soup_straggler_process"] == 1
    assert rows["srnn_soup_straggler_skew_ratio"] == 2.0
    assert rows['srnn_soup_straggler_gens_per_second{process="2"}'] == 3.0
    prom = reg.to_prometheus()
    assert "srnn_soup_straggler_lag_generations" in prom


# ---------------------------------------------------------------------------
# watch console + report fold
# ---------------------------------------------------------------------------


def test_watch_once_snapshot_schema(tmp_path, capsys):
    run = _craft_run_dir(tmp_path)
    assert watch.main([str(run), "--once"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert set(snap["processes"]) == {"0", "1", "2"}
    for lane in snap["processes"].values():
        assert isinstance(lane["generation"], int)
    assert snap["straggler"]["straggler_process"] == 1
    assert snap["health"] == {"nan_frac": 0.0}
    assert snap["last_event_age_s"] is not None
    assert snap["latest_checkpoint"] == "ckpt-gen00000004"


def test_watch_rejects_bad_args(tmp_path, capsys):
    with pytest.raises(SystemExit):
        watch.main(["--once"])          # neither run_dir nor --service
    assert watch.main([str(tmp_path / "nope"), "--once"]) == 2


def test_watch_service_render():
    out = []

    class Out:
        write = staticmethod(out.append)

    watch.render_service({"socket": "/tmp/s.sock", "completed": 10,
                          "queue_depth": 2, "requests_per_sec": 3.2,
                          "uptime_s": 12.5, "distinct_programs": 4,
                          "slo": {"target_p95_ms": 350.0, "p95_ms": 500.0,
                                  "violations": 7}}, Out())
    text = "".join(out)
    assert "3.2 req/s" in text and "p95<=350.0ms" in text
    assert "7 violation(s)" in text


def test_plain_report_folds_worker_heartbeat_lanes(tmp_path):
    run = _craft_run_dir(tmp_path)
    s = summarize(str(run))
    assert s["worker_files"] == ["events-p1.jsonl", "events-p2.jsonl"]
    # each process's stage label is its own lane, workers included
    assert set(s["heartbeats"]) == {"mega_soup@p0/3", "mega_soup@p1/3",
                                    "mega_soup@p2/3"}
    assert s["heartbeats"]["mega_soup@p1/3"]["beats"] == 2
    assert s["heartbeats"]["mega_soup@p1/3"]["last"]["generation"] == 4


def test_histogram_quantile_bucket_upper_bound():
    from srnn_tpu.telemetry.metrics import Histogram

    h = Histogram("t", buckets=(0.1, 0.5, 2.0))
    assert h.quantile(0.95) is None
    for v in [0.05] * 90 + [0.3] * 9:
        h.observe(v, kind="a")
    h.observe(1.0, kind="b")   # label sets merge
    assert h.quantile(0.5) == 0.1
    assert h.quantile(0.95) == 0.5
    assert h.quantile(1.0) == 2.0
    h.observe(100.0, kind="a")
    assert h.quantile(1.0) is None  # falls in +Inf: unknown bound


# ---------------------------------------------------------------------------
# serve: ticket spans + SLO
# ---------------------------------------------------------------------------


def test_serve_ticket_spans_breakdown_and_slo(tmp_path):
    """Every ticket's span family: root duration == the measured
    serve_request_seconds observation, children sum to the root, the
    dispatch child carries stack width + per-tenant amortized cost, and
    a sub-target SLO turns requests into serve_slo_violations_total."""
    from srnn_tpu.serve.service import ExperimentService

    root = str(tmp_path / "svc")
    svc = ExperimentService(root, max_stack=8, slo_p95_ms=0.001)
    with svc:
        t1 = svc.submit("fixpoint_density",
                        {"seed": 0, "trials": 64, "batch": 32}, tenant="a")
        t2 = svc.submit("fixpoint_density",
                        {"seed": 1, "trials": 64, "batch": 32}, tenant="b")
        assert svc.run_pending(window_s=0.05) == 2
        assert svc.wait(t1)["status"] == "done"
        assert svc.wait(t2)["status"] == "done"
        stats = svc.stats()
        reg = svc.registry
        svc.writer.flush()
    rows = [json.loads(l) for l in open(os.path.join(root, "events.jsonl"))]
    spans = [r for r in rows if r.get("kind") == "span"]
    roots = {r["trace_id"]: r for r in spans if r["span"] == "serve.ticket"}
    assert set(roots) == {t1, t2}
    hist_sum = reg.histogram("serve_request_seconds").sum(
        kind="fixpoint_density")
    assert sum(r["seconds"] for r in roots.values()) == \
        pytest.approx(hist_sum, abs=1e-4)
    for ticket, root_row in roots.items():
        assert root_row["stack_k"] == 2 and root_row["mode"] == "stacked"
        children = [r for r in spans
                    if r.get("parent") == root_row["span_id"]
                    and r["trace_id"] == ticket]
        assert [c["span"] for c in children] == \
            ["serve.ticket.queue", "serve.ticket.window",
             "serve.ticket.dispatch", "serve.ticket.publish"]
        assert sum(c["seconds"] for c in children) == \
            pytest.approx(root_row["seconds"], abs=1e-4)
        dispatch = children[2]
        assert dispatch["per_tenant_s"] == \
            pytest.approx(dispatch["seconds"] / 2, abs=1e-5)
        # the window child is bounded by the window the transport slept
        assert children[1]["seconds"] <= 0.05 + 1e-6
    # SLO: 1 microsecond target -> both requests violate; stats + prom
    assert stats["slo"]["target_p95_ms"] == 0.001
    assert stats["slo"]["violations"] == 2
    assert stats["slo"]["p95_ms"] is not None
    assert reg.counter("serve_slo_violations_total").value(
        kind="fixpoint_density") == 2
    prom = open(os.path.join(root, "metrics.prom")).read()
    assert "srnn_serve_slo_violations_total" in prom
    assert 'srnn_serve_ticket_queue_seconds_count{kind="fixpoint_density"}' \
        in prom
    assert "srnn_serve_ticket_window_seconds" in prom
    assert "srnn_serve_ticket_dispatch_seconds" in prom


def test_serve_slo_counter_present_even_without_target(tmp_path):
    """A clean service exposes the SLO counter series eagerly (the load
    bench greps metrics.prom for it), and no target means no violations."""
    from srnn_tpu.serve.service import ExperimentService

    root = str(tmp_path / "svc")
    with ExperimentService(root) as svc:
        assert svc.stats()["slo"] == {"target_p95_ms": None,
                                      "violations": 0, "p95_ms": None}
    assert "srnn_serve_slo_violations_total" in \
        open(os.path.join(root, "metrics.prom")).read()


# ---------------------------------------------------------------------------
# the invariant: observability never perturbs results
# ---------------------------------------------------------------------------


def test_spans_do_not_perturb_results(tmp_path):
    """mega_soup with spans (default) vs --no-spans: weights/uids/PRNG
    bitwise-identical; span rows present only in the default run."""
    import jax

    with_spans = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "41", "--root", str(tmp_path / "a")])
    without = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "41", "--no-spans",
         "--root", str(tmp_path / "b")])
    a = restore_checkpoint(os.path.join(with_spans, "ckpt-gen00000006"))
    b = restore_checkpoint(os.path.join(without, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(a.weights),
                                  np.asarray(b.weights))
    np.testing.assert_array_equal(np.asarray(a.uids), np.asarray(b.uids))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(a.key)),
        np.asarray(jax.random.key_data(b.key)))

    def span_rows(d):
        return [json.loads(l) for l in
                open(os.path.join(d, "events.jsonl"))
                if '"kind": "span"' in l]

    with_rows = span_rows(with_spans)
    assert with_rows and not span_rows(without)
    # chunk roots + their device_wait/host_io children, linked by parent
    roots = [r for r in with_rows if r["span"] == "mega_soup.chunk"]
    assert len(roots) == 3   # 6 generations / checkpoint-every 2
    for root in roots:
        kids = {r["span"] for r in with_rows
                if r.get("parent") == root["span_id"]}
        assert kids == {"mega_soup.device_wait", "mega_soup.host_io"}
    # and the fleet summary reads the same run dir without distress
    s = fleet.fleet_summary(with_spans)
    assert s["processes"]["0"]["spans"] == len(with_rows)


# ---------------------------------------------------------------------------
# the full fleet e2e (heavy: 2-process launcher run)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_e2e_two_process_launcher(tmp_path):
    """The acceptance oracle: a 2-process CPU-mesh launcher run produces
    ONE merged report --fleet timeline with both process lanes and a
    nonzero straggler attribution, watch --once returns per-process
    generations, and the live soup_straggler_* gauges land in
    metrics.prom."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT
    env["SRNN_SETUPS_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    proc = subprocess.run(
        [sys.executable, "-m", "srnn_tpu.distributed.launch",
         "--processes", "2", "--",
         "mega_soup", "--smoke", "--seed", "43", "--sharded",
         "--root", str(tmp_path / "dist")],
        env=env, capture_output=True, text=True, timeout=540,
        cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    run_dir = glob.glob(str(tmp_path / "dist" / "exp-*"))[0]

    s = fleet.fleet_summary(run_dir)
    assert set(s["processes"]) == {"0", "1"}
    for lane in s["processes"].values():
        assert lane["generation"] == 6 and lane["beats"] > 0
        assert lane["spans"] > 0    # workers emit spans too
    att = s["straggler"]
    assert att is not None and att["skew_ratio"] >= 1.0
    assert set(att["gens_per_sec"]) == {0, 1}

    snap = watch.snapshot(run_dir)
    assert {p: lane["generation"] for p, lane in
            snap["processes"].items()} == {"0": 6, "1": 6}

    prom = open(os.path.join(run_dir, "metrics.prom")).read()
    assert "srnn_soup_straggler_skew_ratio" in prom
    assert 'srnn_soup_straggler_gens_per_second{process="1"}' in prom
    # both processes' gather spans made it into the merged timeline
    gathers = [row for row in fleet.merged_timeline(run_dir)[0]
               if row.get("span") == "hostio.fetch_tree"]
    assert {g["process"] for g in gathers} == {0, 1}
