"""Async host/device pipeline invariants (``srnn_tpu/utils/pipeline.py``).

Three layers, mirroring the module's contract:

  * unit: ``BackgroundWriter`` ordering / backpressure / error-latch /
    close-hook semantics, ``ChunkDriver`` deferral depth, ``OverlapMeter``
    attribution, donation-safe ``snapshot``.
  * parity: the pipelined mega loops (soup, multisoup, sharded) produce
    BYTE-identical ``.traj`` streams, exactly-equal checkpoints, and
    bit-identical ``--resume`` continuations vs ``--no-pipeline``.
  * shutdown: no orphan writer threads and fully-flushed stores after
    ``close()`` — including after a simulated mid-chunk crash.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from srnn_tpu.utils import pipeline
from srnn_tpu.utils.pipeline import (BackgroundWriter, ChunkDriver,
                                     OverlapMeter, WriterError, live_threads,
                                     resolve, snapshot, submit_or_run)


# ---------------------------------------------------------------------------
# BackgroundWriter units
# ---------------------------------------------------------------------------


def test_writer_runs_jobs_in_submission_order():
    seen = []
    with BackgroundWriter(name="t-order") as w:
        for i in range(20):
            w.submit(seen.append, i)
        w.flush()
        assert seen == list(range(20))
    assert w.jobs_done == 20


def test_writer_backpressure_bounds_the_producer():
    """submit() blocks while ``maxsize`` jobs are pending — the producer
    can run at most one bounded window ahead."""
    gate = threading.Event()
    w = BackgroundWriter(maxsize=1, name="t-bp")
    try:
        w.submit(gate.wait)   # occupies the worker
        w.submit(lambda: None)  # fills the 1-slot queue

        blocked = threading.Event()

        def producer():
            w.submit(lambda: None)  # must block until the gate opens
            blocked.set()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        assert not blocked.wait(0.2), "submit() returned past a full queue"
        gate.set()
        assert blocked.wait(5.0), "submit() never unblocked after drain"
        t.join(5.0)
    finally:
        gate.set()
        w.close()


def test_writer_error_latches_skips_later_jobs_and_reraises():
    seen = []

    def boom():
        raise RuntimeError("disk gone")

    w = BackgroundWriter(name="t-err")
    try:
        w.submit(seen.append, "before")
        w.submit(boom)
        w.submit(seen.append, "after")  # must be SKIPPED (latched failure)
        with pytest.raises(WriterError, match="disk gone"):
            w.flush()
        assert seen == ["before"]
        assert w.failed
        # a failed writer refuses all further jobs — a silent no-op would
        # let the producer loop run on believing its I/O is landing
        with pytest.raises(WriterError, match="refused"):
            w.submit(seen.append, "rejected")
    finally:
        w.close()  # error already surfaced; close is clean and idempotent
    w.close()


def test_writer_close_hooks_run_even_after_job_failure():
    """The flush/join hook a store registers must run on the error path
    too — frames that DID append stay durable."""
    hooks = []
    w = BackgroundWriter(name="t-hook")
    w.add_close_hook(lambda: hooks.append("joined"))
    w.submit(lambda: (_ for _ in ()).throw(OSError("enospc")))
    with pytest.raises(WriterError, match="enospc"):
        w.close()
    assert hooks == ["joined"]


def test_writer_close_leaves_no_orphan_threads():
    writers = [BackgroundWriter(name=f"t-orphan{i}") for i in range(3)]
    assert len(live_threads()) >= 3
    for w in writers:
        w.close()
    assert live_threads() == []


def test_submit_or_run_inline_when_no_writer():
    seen = []
    submit_or_run(None, seen.append, 1)
    assert seen == [1]


# ---------------------------------------------------------------------------
# ChunkDriver / OverlapMeter units
# ---------------------------------------------------------------------------


def test_chunk_driver_depth_one_defers_exactly_one_finisher():
    ran = []
    d = ChunkDriver(depth=1)
    d.step(lambda: ran.append(1))
    assert ran == []          # held: chunk 2 not dispatched yet
    d.step(lambda: ran.append(2))
    assert ran == [1]         # oldest ran as the 2nd arrived
    d.drain()
    assert ran == [1, 2]


def test_chunk_driver_depth_zero_is_the_blocking_order():
    ran = []
    d = ChunkDriver(depth=0)
    d.step(lambda: ran.append(1))
    assert ran == [1]
    d.drain()
    assert ran == [1]


def test_overlap_meter_attribution_and_gauges():
    from srnn_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    m = OverlapMeter(reg, stage="unit")
    with m.waiting():
        time.sleep(0.02)
    with m.host_io():
        time.sleep(0.01)
    row = m.chunk_done(0.05)
    assert row["device_wait_s"] >= 0.02
    assert row["host_io_s"] >= 0.01
    assert row["device_idle_bound_s"] == pytest.approx(
        0.05 - row["device_wait_s"])
    assert 0.0 < row["overlap_ratio"] <= 1.0
    assert reg.gauge("pipeline_overlap_ratio").value(stage="unit") \
        == pytest.approx(row["overlap_ratio"], abs=1e-4)  # gauge is rounded
    assert reg.counter("pipeline_wall_seconds_total").value(stage="unit") \
        == pytest.approx(0.05)
    s = m.summary()
    assert s["chunks"] == 1 and s["wall_s"] == pytest.approx(0.05)


def test_overlap_meter_folds_writer_busy_seconds_into_host_io():
    with BackgroundWriter(name="t-meter") as w:
        m = OverlapMeter(writer=w)
        w.submit(time.sleep, 0.03)
        w.flush()
        row = m.chunk_done(0.1)
    assert row["host_io_s"] >= 0.03


# ---------------------------------------------------------------------------
# donation-safe snapshots
# ---------------------------------------------------------------------------


def _tiny_config(n=8, train=0):
    from srnn_tpu.soup import SoupConfig
    from srnn_tpu.topology import Topology

    return SoupConfig(topo=Topology("weightwise", width=2, depth=2), size=n,
                      attacking_rate=0.5, train=train, layout="popmajor")


def test_snapshot_survives_donation_of_its_source():
    """The snapshot's device copy must read PRE-donation bytes: resolve()
    after the source state was donated to the next step returns exactly
    the values the source held at snapshot time."""
    import jax

    from srnn_tpu.soup import evolve_step_donated, seed

    cfg = _tiny_config()
    state = seed(cfg, jax.random.key(0))
    state, _ev = evolve_step_donated(cfg, state)  # state is now jax-owned
    before = np.asarray(state.weights).copy()

    snap = snapshot((state.time, state.weights))
    # donate the snapshot's source buffers to the next generation
    state, _ev = evolve_step_donated(cfg, state)
    t, w = resolve(snap)
    assert int(t) == 1
    np.testing.assert_array_equal(w, before)
    assert int(state.time) == 2  # the run itself moved on


# ---------------------------------------------------------------------------
# shutdown: simulated mid-chunk crash
# ---------------------------------------------------------------------------


class _FailingStore:
    """TrajStore stand-in whose append dies after ``ok`` frames — the
    simulated mid-chunk crash (ENOSPC / yanked disk) under the writer."""

    def __init__(self, store, ok):
        self._store = store
        self._ok = ok
        self.appends = 0

    def append(self, *args):
        self.appends += 1
        if self.appends > self._ok:
            raise OSError("simulated mid-chunk crash")
        self._store.append(*args)

    def __getattr__(self, name):
        return getattr(self._store, name)


def test_capture_crash_mid_chunk_flushes_survivors_and_joins(tmp_path):
    """A writer-job crash mid-chunk surfaces as WriterError, leaves NO
    orphan threads, and the frames appended BEFORE the crash are durable
    (the store's join hook ran on the error path)."""
    import jax

    from srnn_tpu.utils import read_store
    from srnn_tpu.utils.capture import evolve_captured
    from srnn_tpu.utils.trajstore import TrajStore

    from srnn_tpu.soup import seed

    cfg = _tiny_config()
    state = seed(cfg, jax.random.key(0))
    path = str(tmp_path / "crash.traj")
    store = TrajStore(path, n_particles=cfg.size,
                      n_weights=cfg.topo.num_weights)
    failing = _FailingStore(store, ok=2)
    with pytest.raises(WriterError, match="simulated mid-chunk crash"):
        evolve_captured(cfg, state, generations=5, store=failing, every=1)
    store.close()
    assert live_threads() == []  # the private writer joined on the way out
    out = read_store(path)
    assert out["generations"].tolist() == [1, 2]  # survivors durable


# ---------------------------------------------------------------------------
# end-to-end parity: pipelined vs --no-pipeline mega loops
# ---------------------------------------------------------------------------


def _file_bytes(path):
    with open(path, "rb") as f:
        return f.read()


def _assert_soup_ckpt_equal(dir_a, dir_b, gens):
    from srnn_tpu.experiment import restore_checkpoint

    import jax

    for g in gens:
        a = restore_checkpoint(os.path.join(dir_a, f"ckpt-gen{g:08d}"))
        b = restore_checkpoint(os.path.join(dir_b, f"ckpt-gen{g:08d}"))
        np.testing.assert_array_equal(np.asarray(a.weights),
                                      np.asarray(b.weights))
        np.testing.assert_array_equal(np.asarray(a.uids), np.asarray(b.uids))
        assert int(a.time) == int(b.time) == g
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(a.key)),
            np.asarray(jax.random.key_data(b.key)))


def test_mega_soup_pipeline_parity_and_resume(tmp_path):
    """Pipelined captured stream + every checkpoint + a resumed
    continuation are bit-identical to the blocking (--no-pipeline) run."""
    from srnn_tpu.setups import REGISTRY

    common = ["--smoke", "--capture-every", "1"]
    d_block = REGISTRY["mega_soup"](
        common + ["--root", str(tmp_path / "block"), "--no-pipeline"])
    d_pipe = REGISTRY["mega_soup"](
        common + ["--root", str(tmp_path / "pipe")])
    assert live_threads() == []  # the run's writer closed behind itself

    assert _file_bytes(os.path.join(d_pipe, "soup.traj")) \
        == _file_bytes(os.path.join(d_block, "soup.traj"))
    _assert_soup_ckpt_equal(d_pipe, d_block, (2, 4, 6))
    # the pipelined run recorded its overlap attribution
    rows = [json.loads(l) for l in
            open(os.path.join(d_pipe, "events.jsonl"))]
    pipe_rows = [r for r in rows if r.get("kind") == "pipeline"]
    assert pipe_rows and pipe_rows[-1]["pipelined"] \
        and pipe_rows[-1]["chunks"] == 3

    # a PIPELINED half-run resumed PIPELINED lands bit-identical to the
    # uninterrupted BLOCKING reference — stream and final checkpoint
    d_half = REGISTRY["mega_soup"](
        common + ["--root", str(tmp_path / "half"), "--generations", "4"])
    d_resumed = REGISTRY["mega_soup"](["--smoke", "--resume", d_half])
    assert d_resumed == d_half
    assert _file_bytes(os.path.join(d_half, "soup.traj")) \
        == _file_bytes(os.path.join(d_block, "soup.traj"))
    _assert_soup_ckpt_equal(d_half, d_block, (6,))


def test_mega_soup_sharded_pipeline_parity(tmp_path):
    """The sharded chunk loop's pipelined capture shard is byte-identical
    to its blocking twin (sharding-preserving snapshots, shard-local
    reads on the writer)."""
    from srnn_tpu.setups import REGISTRY

    common = ["--smoke", "--sharded", "--capture-every", "1"]
    d_block = REGISTRY["mega_soup"](
        common + ["--root", str(tmp_path / "block"), "--no-pipeline"])
    d_pipe = REGISTRY["mega_soup"](
        common + ["--root", str(tmp_path / "pipe")])
    assert live_threads() == []
    assert _file_bytes(os.path.join(d_pipe, "soup.traj")) \
        == _file_bytes(os.path.join(d_block, "soup.traj"))
    _assert_soup_ckpt_equal(d_pipe, d_block, (2, 4, 6))


def test_mega_multisoup_pipeline_parity(tmp_path):
    """Per-type captured streams and the MultiSoupState checkpoints of the
    heterogeneous loop are bit-identical pipelined vs blocking.

    Runs as REAL CLI subprocesses for the same reason as
    test_setups.test_mega_multisoup_per_type_capture_survives_resume: the
    in-process multisoup capture flow can poison the shared XLA CPU
    client for later unrelated compiles (upstream bug; isolation is the
    durable fix)."""
    import subprocess
    import sys

    from srnn_tpu.experiment import restore_multi_checkpoint

    def cli(*argv):
        env = dict(os.environ)
        env["SRNN_SETUPS_PLATFORM"] = "cpu"  # never dial the tunnel
        proc = subprocess.run(
            [sys.executable, "-m", "srnn_tpu.setups", "mega_multisoup",
             *argv], stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=300, env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        out = proc.stdout.decode()
        assert proc.returncode == 0, out
        return out.strip().splitlines()[-1]  # run dir printed last

    common = ("--smoke", "--capture-every", "2")
    d_block = cli(*common, "--root", str(tmp_path / "block"),
                  "--no-pipeline")
    d_pipe = cli(*common, "--root", str(tmp_path / "pipe"))

    for t in range(3):
        assert _file_bytes(os.path.join(d_pipe, f"soup.t{t}.traj")) \
            == _file_bytes(os.path.join(d_block, f"soup.t{t}.traj")), \
            f"type {t} stream differs"
    a = restore_multi_checkpoint(os.path.join(d_pipe, "ckpt-gen00000006"))
    b = restore_multi_checkpoint(os.path.join(d_block, "ckpt-gen00000006"))
    for t in range(3):
        np.testing.assert_array_equal(np.asarray(a.weights[t]),
                                      np.asarray(b.weights[t]))
        np.testing.assert_array_equal(np.asarray(a.uids[t]),
                                      np.asarray(b.uids[t]))
    assert int(a.time) == int(b.time) == 6


# ---------------------------------------------------------------------------
# heartbeat satellite: amortized fsync + writer routing
# ---------------------------------------------------------------------------


def test_heartbeat_fsync_every_amortizes_but_always_flushes(
        tmp_path, monkeypatch):
    from srnn_tpu.experiment import Experiment
    from srnn_tpu.telemetry import Heartbeat

    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (synced.append(fd),
                                                 real_fsync(fd)))
    with Experiment("hb-fsync", root=str(tmp_path)) as exp:
        hb = Heartbeat(exp, stage="unit", fsync_every=3)
        for g in range(6):
            hb.beat(generation=g)
        run_dir = exp.dir
        n_synced = len(synced)
    beats = [json.loads(l) for l in
             open(os.path.join(run_dir, "events.jsonl"))
             if '"heartbeat"' in l]
    assert len(beats) == 6          # every row flushed regardless
    assert n_synced == 2            # beats 0 and 3 paid the fsync


def test_heartbeat_rows_route_through_writer(tmp_path):
    from srnn_tpu.experiment import Experiment
    from srnn_tpu.telemetry import Heartbeat

    with Experiment("hb-writer", root=str(tmp_path)) as exp:
        with BackgroundWriter(name="t-hb") as w:
            hb = Heartbeat(exp, stage="unit", writer=w)
            hb.beat(generation=1)
            w.flush()
        run_dir = exp.dir
    beats = [json.loads(l) for l in
             open(os.path.join(run_dir, "events.jsonl"))
             if '"heartbeat"' in l]
    assert [b["generation"] for b in beats] == [1]
