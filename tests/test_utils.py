"""Profiling harness, NaN provenance, determinism guarantees."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import checkify

from srnn_tpu import Topology, init_population
from srnn_tpu.fixtures import identity_fixpoint_flat
from srnn_tpu.soup import SoupConfig, evolve, seed
from srnn_tpu.utils import (checked_apply_to_weights, divergence_onset,
                            timed, trace)


def test_timed_stats():
    topo = Topology("weightwise")
    pop = init_population(topo, jax.random.key(0), 32)

    @jax.jit
    def f(w):
        return w * 2.0

    stats = timed(f, pop, iters=4, warmup=1)
    assert stats["iters"] == 4 and len(stats["times_s"]) == 4
    assert 0 < stats["min_s"] <= stats["mean_s"] <= stats["max_s"]


@pytest.mark.slow
def test_trace_writes_profile(tmp_path):
    d = str(tmp_path / "prof")
    with trace(d):
        jnp.ones(8).sum().block_until_ready()
    found = [f for _root, _d, files in os.walk(d) for f in files]
    assert found  # profiler emitted something


def test_checked_apply_passes_and_raises():
    topo = Topology("weightwise")
    flat = identity_fixpoint_flat(topo)
    out = checked_apply_to_weights(topo, flat, flat)
    np.testing.assert_allclose(np.asarray(out), np.asarray(flat), atol=1e-6)

    # a self net scaled to overflow f32 in one matmul chain must be caught
    blown = flat * 1e30
    with pytest.raises(checkify.JaxRuntimeError, match="non-finite"):
        checked_apply_to_weights(topo, blown, jnp.ones_like(flat) * 1e30)


def test_divergence_onset():
    topo = Topology("weightwise")
    cfg = SoupConfig(topo=topo, size=8, attacking_rate=0.0, learn_from_rate=0.0,
                     train=0)
    state = seed(cfg, jax.random.key(0))
    # plant one particle that blows up under self-attack... but attack rate 0
    # means nothing changes; plant an already-divergent particle instead
    w = state.weights.at[3].set(jnp.nan)
    state = state._replace(weights=w)
    onset, _final = divergence_onset(cfg, state, generations=4)
    onset = np.asarray(onset)
    assert onset[3] == 0          # divergent before any generation
    assert (onset[np.arange(8) != 3] == -1).all()


def test_soup_determinism_same_key():
    """Same key => bit-identical soup; different key => different
    (SURVEY §5 race-detection row: determinism is the sanitizer)."""
    cfg = SoupConfig(topo=Topology("weightwise"), size=10,
                     attacking_rate=0.3, learn_from_rate=0.2,
                     learn_from_severity=1, train=1,
                     remove_divergent=True, remove_zero=True)
    a = evolve(cfg, seed(cfg, jax.random.key(5)), generations=4)
    b = evolve(cfg, seed(cfg, jax.random.key(5)), generations=4)
    c = evolve(cfg, seed(cfg, jax.random.key(6)), generations=4)
    np.testing.assert_array_equal(np.asarray(a.weights), np.asarray(b.weights))
    np.testing.assert_array_equal(np.asarray(a.uids), np.asarray(b.uids))
    assert not np.array_equal(np.asarray(a.weights), np.asarray(c.weights))


def test_printing_object_reference_surface(capsys):
    """PrintingObject mirrors util.py:1-39: silent default, fluent setters,
    SilenceSignal restores the previous value."""
    from srnn_tpu.utils import PrintingObject

    class Thing(PrintingObject):
        pass

    t = Thing()
    assert t.is_silent() and t.get_silence()
    t._print("hidden")
    assert capsys.readouterr().out == ""
    assert t.unset_silence() is t and not t.silent
    t._print("shown")
    assert capsys.readouterr().out == "shown\n"
    with t.silence():
        assert t.silent
        t._print("muted")
    assert not t.silent  # restored
    assert capsys.readouterr().out == ""
    assert t.with_silence().is_silent()
