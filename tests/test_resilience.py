"""Elastic run supervisor (srnn_tpu/resilience/): fault taxonomy, retry/
backoff, topology re-ramp, SIGTERM preemption, the deterministic chaos
harness, torn-checkpoint hardening, and the writer's transient-I/O retry.

The e2e oracle discipline: an UNCHANGED-topology recovery must replay
bit-exactly against an uninterrupted run (resume is bit-exact, so
recovery == resume must inherit it); a SHRUNK-topology re-ramp rides the
sharded-vs-unsharded bitwise parity the parallel suite already proves,
so on the XLA-CPU backend it is asserted bitwise too (real mixed-TPU
topologies may add float noise — PARITY.md's documented tolerance tier).
"""

import errno
import glob
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from srnn_tpu.experiment import restore_checkpoint
from srnn_tpu.resilience import (EXIT_PREEMPTED_CLEAN,
                                 EXIT_RETRIES_EXHAUSTED, BackoffPolicy,
                                 ChaosMonkey, Preempted, Supervisor,
                                 classify_fault, parse_schedule)
from srnn_tpu.setups import REGISTRY
from srnn_tpu.setups.common import checkpoint_intact, latest_checkpoint
from srnn_tpu.utils.pipeline import (BackgroundWriter, StallError,
                                     WriterError)

FAST = ["--backoff-base-s", "0.01", "--backoff-max-s", "0.05"]


# ---------------------------------------------------------------------------
# fault taxonomy
# ---------------------------------------------------------------------------


def test_classify_fault_taxonomy():
    from jaxlib.xla_extension import XlaRuntimeError

    assert classify_fault(XlaRuntimeError("INTERNAL: device halted")) \
        == "device_loss"
    assert classify_fault(XlaRuntimeError("UNAVAILABLE: tpu worker gone")) \
        == "device_loss"
    assert classify_fault(
        RuntimeError("tpu received a goaway from the system")) \
        == "device_loss"
    assert classify_fault(StallError("finisher wedged")) == "stall"
    assert classify_fault(WriterError("job 'x' failed")) == "io"
    assert classify_fault(OSError(errno.EIO, "flaky disk")) == "io"
    assert classify_fault(OSError(errno.ENOSPC, "disk full")) == "io"
    assert classify_fault(Preempted(42)) == "preempt"
    # user/programming errors must NEVER be retried
    assert classify_fault(FileNotFoundError(2, "no config.json")) == "fatal"
    assert classify_fault(PermissionError(13, "denied")) == "fatal"
    assert classify_fault(ValueError("bad shape")) == "fatal"
    assert classify_fault(SystemExit(2)) == "fatal"
    assert classify_fault(KeyboardInterrupt()) == "fatal"
    # DETERMINISTIC XLA statuses repeat on retry (and an OOM gets WORSE
    # under budget halving) — fatal despite the XlaRuntimeError type
    assert classify_fault(XlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 4.0G")) == "fatal"
    assert classify_fault(XlaRuntimeError(
        "INVALID_ARGUMENT: shapes disagree")) == "fatal"
    # a WriterError is only as retryable as what it wraps
    fatal_cause = WriterError("job 'append' failed")
    fatal_cause.__cause__ = FileNotFoundError(2, "store dir gone")
    assert classify_fault(fatal_cause) == "fatal"
    io_cause = WriterError("job 'append' failed")
    io_cause.__cause__ = OSError(errno.EIO, "flaky")
    assert classify_fault(io_cause) == "io"
    # a deterministic logic bug inside a writer job repeats on retry
    bug_cause = WriterError("job 'update_registry' failed")
    bug_cause.__cause__ = TypeError("bad arg")
    assert classify_fault(bug_cause) == "fatal"
    # a device loss surfacing through a deferred resolve ON the writer
    # thread keeps its classification (and its re-ramp)
    dev_cause = WriterError("job 'update_registry' failed")
    dev_cause.__cause__ = XlaRuntimeError("INTERNAL: device halted")
    assert classify_fault(dev_cause) == "device_loss"
    # writer-internal refusals (closed/latched, no cause) stay io
    assert classify_fault(WriterError("job refused")) == "io"


def test_backoff_deterministic_capped_and_jittered():
    a = BackoffPolicy(base_s=1.0, max_s=8.0, jitter=0.25, seed=7)
    b = BackoffPolicy(base_s=1.0, max_s=8.0, jitter=0.25, seed=7)
    seq_a = [a.delay(k) for k in range(6)]
    seq_b = [b.delay(k) for k in range(6)]
    assert seq_a == seq_b  # same seed -> same jitter stream, reproducible
    c = BackoffPolicy(base_s=1.0, max_s=8.0, jitter=0.25, seed=8)
    assert [c.delay(k) for k in range(6)] != seq_a
    for k, d in enumerate(seq_a):
        nominal = min(1.0 * 2 ** k, 8.0)
        assert 0.75 * nominal <= d <= 1.25 * nominal
    assert BackoffPolicy(base_s=1.0, jitter=0.0).delay(2) == 4.0


# ---------------------------------------------------------------------------
# chaos schedule
# ---------------------------------------------------------------------------


def test_parse_schedule_kinds_args_and_errors():
    evs = parse_schedule("device_loss@4:2, stall@6:9.5,writer@3,sigterm@8")
    assert [(e.kind, e.at, e.arg) for e in evs] == [
        ("writer", 3, None), ("device_loss", 4, 2.0), ("stall", 6, 9.5),
        ("sigterm", 8, None)]
    with pytest.raises(ValueError, match="unknown chaos kind"):
        parse_schedule("meteor@4")
    with pytest.raises(ValueError, match="bad chaos entry"):
        parse_schedule("device_loss")
    with pytest.raises(ValueError, match="negative"):
        parse_schedule("stall@-1")
    with pytest.raises(ValueError, match="1-based"):
        parse_schedule("writer@0")  # counter starts at 1: would never fire


def test_chaos_from_args_validates_stall_needs_timeout():
    class A:
        chaos = "stall@4"
        stall_timeout_s = 0.0

    with pytest.raises(SystemExit, match="stall-timeout"):
        ChaosMonkey.from_args(A())
    A.stall_timeout_s = 2.0
    assert ChaosMonkey.from_args(A()) is not None

    class B:
        chaos = None

    assert ChaosMonkey.from_args(B()) is None


def test_chaos_device_loss_fires_once_and_forces_live():
    from jaxlib.xla_extension import XlaRuntimeError

    m = ChaosMonkey(parse_schedule("device_loss@4:2"))
    m.chunk_start(2)  # before the scheduled generation: nothing
    with pytest.raises(XlaRuntimeError, match="simulated device loss"):
        m.chunk_start(4)
    assert m.forced_live == 2
    m.chunk_start(6)  # fired events never re-fire (recovery can't loop)
    assert not m.pending
    # the override covers exactly ONE recovery probe: a later
    # un-annotated loss must probe the real topology
    assert m.take_forced_live() == 2
    assert m.take_forced_live() == 0


def test_chaos_condemned_finisher_never_runs_and_aborts():
    m = ChaosMonkey(parse_schedule("stall@2"))
    ran = []
    fin = m.wrap_finisher(lambda: ran.append(1), gen_end=2)
    assert fin is not m  # wrapped
    t = threading.Thread(target=fin, daemon=True)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()          # held, finisher NOT run
    m.abort_pending()
    t.join(timeout=5)
    assert not t.is_alive() and ran == []
    # later chunks get their real finisher back (event consumed)
    assert m.wrap_finisher(lambda: None, gen_end=4) is not fin

    # a SECOND stall event after a recovery must still HOLD — the
    # released flag is per condemned finisher, never a permanent disarm
    m2 = ChaosMonkey(parse_schedule("stall@2,stall@6"))
    first = m2.wrap_finisher(lambda: ran.append("a"), gen_end=2)
    m2.abort_pending()                       # recovery 1 releases it
    second = m2.wrap_finisher(lambda: ran.append("b"), gen_end=6)
    t2 = threading.Thread(target=second, daemon=True)
    t2.start()
    time.sleep(0.05)
    assert t2.is_alive(), "second condemned finisher must block too"
    m2.abort_pending()
    t2.join(timeout=5)
    assert not t2.is_alive() and ran == []
    del first


def test_chaos_writer_poisons_nth_job_and_names_it():
    m = ChaosMonkey(parse_schedule("writer@2"))
    seen = []
    w = BackgroundWriter(name="t-chaos")
    m.attach_writer(w)

    def first():
        seen.append("first")

    def save_checkpoint():  # the label the latch should carry
        seen.append("second")

    w.submit(first)
    w.submit(save_checkpoint)   # poisoned in its place
    with pytest.raises(WriterError, match="save_checkpoint"):
        w.close()
    assert seen == ["first"]


# ---------------------------------------------------------------------------
# supervisor retry loop (unit level, no jax dispatch)
# ---------------------------------------------------------------------------


def _fast_policy(n):
    return BackoffPolicy(max_restarts=n, base_s=0.001, max_s=0.002,
                         jitter=0.0)


def test_supervisor_recovers_then_returns():
    calls = []

    def run_once(args, ctx):
        calls.append(ctx.restarts)
        if len(calls) < 3:
            raise OSError(errno.EIO, "flaky")
        return "run-dir"

    sup = Supervisor(_fast_policy(5))
    out = sup.run(run_once, args=type("A", (), {"resume": None})())
    assert out == "run-dir"
    assert calls == [0, 1, 2]
    from srnn_tpu.resilience import supervisor as sv

    assert sv.LAST_REPORT["outcome"] == "recovered"
    assert sv.LAST_REPORT["restarts"] == 2
    assert len(sv.LAST_REPORT["recoveries"]) == 2


def test_supervisor_exhausts_with_exit_code():
    def run_once(args, ctx):
        raise OSError(errno.EIO, "always broken")

    sup = Supervisor(_fast_policy(2))
    with pytest.raises(SystemExit) as ei:
        sup.run(run_once, args=type("A", (), {"resume": None})())
    assert ei.value.code == EXIT_RETRIES_EXHAUSTED
    from srnn_tpu.resilience import supervisor as sv

    assert sv.LAST_REPORT["outcome"] == "exhausted"


def test_supervisor_fatal_and_unsupervised_propagate_unchanged():
    def bad(args, ctx):
        raise ValueError("logic error")

    with pytest.raises(ValueError, match="logic error"):
        Supervisor(_fast_policy(5)).run(
            bad, args=type("A", (), {"resume": None})())

    def stall(args, ctx):
        raise StallError("wedged")

    # --max-restarts 0: retryable kinds keep their original type too
    with pytest.raises(StallError, match="wedged"):
        Supervisor(_fast_policy(0)).run(
            stall, args=type("A", (), {"resume": None})())


def test_reramp_ladder_survivors_then_halving():
    """Verified survivors win; a REPEATED loss with no observed shrink
    halves; floors at one device; a FIRST loss that probes whole is a
    transient blip (same topology retried); unsharded attempts (no mesh
    seen) never re-ramp."""
    from jaxlib.xla_extension import XlaRuntimeError

    chaos = ChaosMonkey([])
    sup = Supervisor(_fast_policy(10), chaos=chaos)
    sup.ctx.last_seen_devices = 8
    args = type("A", (), {"resume": None})()
    loss = XlaRuntimeError("INTERNAL: device halted")
    chaos.forced_live = 4
    sup._recover("device_loss", loss, args)
    assert sup.ctx.device_budget == 4 and sup.ctx.recoveries[-1]["reramped"]
    assert sup.ctx.survivor_devices is not None \
        and len(sup.ctx.survivor_devices) == 4
    # repeat with no shrink observed (real probe: all devices alive)
    # -> halve
    sup._recover("device_loss", loss, args)
    assert sup.ctx.device_budget == 2
    sup._recover("device_loss", loss, args)
    assert sup.ctx.device_budget == 1
    sup._recover("device_loss", loss, args)
    assert sup.ctx.device_budget == 1  # floor, and NOT another re-ramp
    assert not sup.ctx.recoveries[-1]["reramped"]

    # FIRST loss, probe shows the full topology alive: transient blip,
    # budget unchanged, no re-ramp counted
    blip = Supervisor(_fast_policy(10))
    blip.ctx.last_seen_devices = 8
    blip._recover("device_loss", loss, args)
    assert blip.ctx.device_budget == 8
    assert not blip.ctx.recoveries[-1]["reramped"]

    unsharded = Supervisor(_fast_policy(10))
    unsharded._recover("device_loss", loss, args)
    assert unsharded.ctx.device_budget is None
    assert not unsharded.ctx.recoveries[-1]["reramped"]


def test_mesh_devices_snaps_to_population_divisor():
    """A re-ramped device count the population cannot shard over snaps
    DOWN to the nearest divisor instead of handing the resume attempt a
    fatal divisibility error."""
    from srnn_tpu.resilience import AttemptContext

    ctx = AttemptContext(device_budget=3)
    ctx.shard_sizes = (64,)
    assert len(ctx.mesh_devices()) == 2   # 3 does not divide 64 -> 2
    ctx.device_budget = 8
    assert len(ctx.mesh_devices()) == 8   # exact fit untouched
    ctx.shard_sizes = (9,)
    assert len(ctx.mesh_devices()) == 3   # 8,7,6,5,4 rejected, 3 | 9
    ctx.shard_sizes = ()
    assert len(ctx.mesh_devices()) == 8   # no sizes published: clamp only


# ---------------------------------------------------------------------------
# torn-checkpoint hardening
# ---------------------------------------------------------------------------


def _fake_ckpt(run_dir, gen, marker=True, torn=False):
    from srnn_tpu.experiment import CKPT_OK_MARKER

    d = os.path.join(run_dir, f"ckpt-gen{gen:08d}")
    os.makedirs(os.path.join(d, "d"))
    with open(os.path.join(d, "_METADATA"), "w") as f:
        f.write("{}")
    with open(os.path.join(d, "d", "data"), "w") as f:
        f.write("" if torn else "payload")
    if marker:
        with open(os.path.join(d, CKPT_OK_MARKER), "w") as f:
            f.write('{"time": %d}\n' % gen)
    return d


def test_latest_checkpoint_skips_torn_and_prefers_markers(tmp_path,
                                                          capsys):
    run = str(tmp_path)
    ok2 = _fake_ckpt(run, 2, marker=True)
    ok4 = _fake_ckpt(run, 4, marker=False)            # legacy, healthy
    _fake_ckpt(run, 6, marker=False, torn=True)       # truncated file
    os.makedirs(os.path.join(run, "ckpt-gen00000008.orbax-checkpoint-tmp-1"))
    assert latest_checkpoint(run) == ok4
    assert "skipping torn checkpoint" in capsys.readouterr().err
    # a marker certifies a dir even when a sidecar file is empty (the
    # marker is published only after orbax finished)
    assert checkpoint_intact(ok2)
    import shutil

    shutil.rmtree(ok4)
    assert latest_checkpoint(run) == ok2
    shutil.rmtree(ok2)
    with pytest.raises(FileNotFoundError, match="torn candidate"):
        latest_checkpoint(run)


def test_real_checkpoints_carry_marker_and_intact(tmp_path):
    import jax

    from srnn_tpu.experiment import CKPT_OK_MARKER
    from srnn_tpu.soup import SoupConfig, seed
    from srnn_tpu.topology import Topology

    cfg = SoupConfig(topo=Topology("weightwise", width=2, depth=2), size=8)
    from srnn_tpu.experiment import save_checkpoint

    p = save_checkpoint(str(tmp_path / "ckpt-gen00000002"),
                        seed(cfg, jax.random.key(0)))
    assert os.path.exists(os.path.join(p, CKPT_OK_MARKER))
    assert checkpoint_intact(p)
    assert json.load(open(os.path.join(p, CKPT_OK_MARKER)))["time"] == 0


# ---------------------------------------------------------------------------
# background-writer transient-I/O retry
# ---------------------------------------------------------------------------


def test_writer_retries_eintr_then_succeeds():
    seen = []
    fails = [errno.EINTR, errno.EAGAIN]

    def flaky_append():
        if fails:
            raise OSError(fails.pop(0), "interrupted")
        seen.append("landed")

    w = BackgroundWriter(name="t-retry", retry_backoff_s=0.001)
    w.submit(flaky_append)
    w.flush()
    assert seen == ["landed"]
    assert w.jobs_retried == 2 and not w.failed
    w.close()


def test_writer_enospc_grace_then_latch_names_job():
    # within the grace window ENOSPC retries until the disk "frees up"
    seen = []
    fails = [errno.ENOSPC]

    def append_frame():
        if fails:
            raise OSError(fails.pop(0), "no space")
        seen.append("landed")

    w = BackgroundWriter(name="t-enospc", retry_backoff_s=0.001,
                         enospc_grace_s=5.0)
    w.submit(append_frame)
    w.flush()
    assert seen == ["landed"] and not w.failed
    w.close()

    # grace exhausted (0): the permanent latch trips and NAMES the job
    def save_checkpoint():
        raise OSError(errno.ENOSPC, "no space")

    w2 = BackgroundWriter(name="t-enospc0", enospc_grace_s=0.0)
    w2.submit(save_checkpoint)
    with pytest.raises(WriterError, match="'save_checkpoint'"):
        w2.close()


def test_writer_retry_budget_bounds_transient_errors():
    def always_eintr():
        raise OSError(errno.EINTR, "interrupted forever")

    w = BackgroundWriter(name="t-budget", io_retries=2,
                         retry_backoff_s=0.001)
    w.submit(always_eintr)
    with pytest.raises(WriterError, match="'always_eintr'"):
        w.close()
    assert w.jobs_retried == 2  # retried exactly the budget, then latched


# ---------------------------------------------------------------------------
# mesh-from-survivors re-ramp helpers
# ---------------------------------------------------------------------------


def test_slice_groups_and_reramp_mesh_from_survivors():
    from srnn_tpu.parallel import reramp_soup_mesh, slice_groups

    class Dev:
        def __init__(self, i, s):
            self.id = i
            self.slice_index = s
            self.process_index = 0

    # 2 whole slices of 4 -> (slices, soup) mesh
    devs = [Dev(i, i // 4) for i in range(8)]
    groups = slice_groups(devs)
    assert [len(g) for g in groups] == [4, 4]
    m = reramp_soup_mesh(devs)
    assert m.axis_names == ("slices", "soup") and m.devices.shape == (2, 4)
    # slice 1 lost two chips: only one WHOLE slice remains -> 1-D ICI mesh
    survivors = [d for d in devs if not (d.slice_index == 1 and d.id >= 6)]
    m = reramp_soup_mesh(survivors)
    assert m.axis_names == ("soup",) and m.devices.shape == (4,)
    with pytest.raises(ValueError, match="no surviving devices"):
        reramp_soup_mesh([])
    # real CPU devices expose no slice_index -> one group, 1-D mesh
    import jax

    m = reramp_soup_mesh(jax.devices())
    assert m.axis_names == ("soup",)
    assert m.devices.size == len(jax.devices())


def test_probe_devices_verify_roundtrips():
    import jax

    from srnn_tpu.parallel import probe_devices

    assert len(probe_devices()) == len(jax.devices())
    assert len(probe_devices(verify=True)) == len(jax.devices())


# ---------------------------------------------------------------------------
# chaos e2e on CPU: the recovery paths against the real mega loops
# ---------------------------------------------------------------------------


def test_device_loss_recovery_bit_exact_and_sigterm_resumable(tmp_path):
    """The acceptance triptych, sharing one uninterrupted oracle run:
    (a) a scheduled device loss mid-run is survived via backoff+restore
    and the finished state is BIT-identical to the uninterrupted run
    (unchanged topology => recovery == resume == bit-exact); (b) SIGTERM
    produces a preempted-clean exit whose final checkpoint resumes to the
    same bit-identical end state."""
    oracle = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path / "oracle")])
    want = restore_checkpoint(os.path.join(oracle, "ckpt-gen00000006"))

    # (a) device loss at generation 4, recovered in-process
    d = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path / "loss"),
         "--chaos", "device_loss@4"] + FAST)
    got = restore_checkpoint(os.path.join(d, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(want.weights),
                                  np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(want.uids),
                                  np.asarray(got.uids))
    log = open(os.path.join(d, "log.txt")).read()
    assert "supervisor: restart 1 after device_loss fault" in log
    prom = open(os.path.join(d, "metrics.prom")).read()
    assert "srnn_soup_restarts_total 1" in prom
    events = [json.loads(l) for l in
              open(os.path.join(d, "events.jsonl"))]
    assert any(e.get("kind") == "restart" for e in events)

    # (b) SIGTERM at the gen-2 boundary: graceful drain, exit 75,
    # resumable final checkpoint
    with pytest.raises(SystemExit) as ei:
        REGISTRY["mega_soup"](
            ["--smoke", "--root", str(tmp_path / "term"),
             "--chaos", "sigterm@2"] + FAST)
    assert ei.value.code == EXIT_PREEMPTED_CLEAN
    d_term = glob.glob(str(tmp_path / "term" / "exp-*"))[0]
    assert latest_checkpoint(d_term).endswith("ckpt-gen00000004")
    assert "SIGTERM honored" in open(os.path.join(d_term, "log.txt")).read()
    d_resumed = REGISTRY["mega_soup"](["--smoke", "--resume", d_term])
    assert d_resumed == d_term
    got = restore_checkpoint(os.path.join(d_term, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(want.weights),
                                  np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(want.uids),
                                  np.asarray(got.uids))


@pytest.mark.slow
def test_reramp_shrunk_topology_completes_with_matching_census(tmp_path):
    """Acceptance: a 2-shard run loses its mesh mid-run and re-ramps onto
    1 device; the run completes and the final population matches the
    uninterrupted 2-shard twin.  On the XLA-CPU backend the sharded path
    is bitwise vs single-device (tests/test_parallel.py), so the census
    matches EXACTLY here; on mixed real topologies the documented
    tolerance tier (PARITY.md) applies."""
    d = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path / "reramp"), "--sharded",
         "--max-devices", "2", "--chaos", "device_loss@4:1"] + FAST)
    log = open(os.path.join(d, "log.txt")).read()
    assert "re-ramped to 1 device(s)" in log
    prom = open(os.path.join(d, "metrics.prom")).read()
    assert "srnn_soup_topology_reramps_total 1" in prom

    oracle = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path / "oracle"), "--sharded",
         "--max-devices", "2"])
    want = restore_checkpoint(os.path.join(oracle, "ckpt-gen00000006"))
    got = restore_checkpoint(os.path.join(d, "ckpt-gen00000006"))
    # fixpoint census: identical class histograms...
    from srnn_tpu.engine import classify_batch
    from srnn_tpu.topology import Topology

    topo = Topology("weightwise", width=2, depth=2)
    census_want = np.bincount(np.asarray(
        classify_batch(topo, want.weights, 1e-4)), minlength=5)
    census_got = np.bincount(np.asarray(
        classify_batch(topo, got.weights, 1e-4)), minlength=5)
    np.testing.assert_array_equal(census_want, census_got)
    # ...and on this backend, bitwise state parity outright
    np.testing.assert_array_equal(np.asarray(want.weights),
                                  np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(want.uids),
                                  np.asarray(got.uids))


@pytest.mark.slow
def test_multisoup_device_loss_recovery_bit_exact(tmp_path):
    """The heterogeneous loop shares the supervisor contract: a device
    loss mid-run recovers to a bit-identical end state."""
    from srnn_tpu.experiment import restore_multi_checkpoint

    oracle = REGISTRY["mega_multisoup"](
        ["--smoke", "--root", str(tmp_path / "oracle")])
    want = restore_multi_checkpoint(os.path.join(oracle, "ckpt-gen00000006"))
    d = REGISTRY["mega_multisoup"](
        ["--smoke", "--root", str(tmp_path / "loss"),
         "--chaos", "device_loss@4"] + FAST)
    got = restore_multi_checkpoint(os.path.join(d, "ckpt-gen00000006"))
    for ww, wg in zip(want.weights, got.weights):
        np.testing.assert_array_equal(np.asarray(ww), np.asarray(wg))
    for uw, ug in zip(want.uids, got.uids):
        np.testing.assert_array_equal(np.asarray(uw), np.asarray(ug))
    assert "supervisor: restart 1 after device_loss fault" in \
        open(os.path.join(d, "log.txt")).read()


@pytest.mark.slow
def test_sigkill_mid_run_resume_traj_bit_identical(tmp_path):
    """The kill-and-resume e2e: a mega_soup CHILD PROCESS is SIGKILLed
    mid-run (no cleanup of any kind), the run is resumed from the newest
    surviving checkpoint, and the captured .traj stream is bit-identical
    to an uninterrupted run's — frames across the kill boundary
    included."""
    from srnn_tpu.utils import read_store

    oracle = REGISTRY["mega_soup"](
        ["--smoke", "--root", str(tmp_path / "oracle"),
         "--capture-every", "1"])
    want = read_store(os.path.join(oracle, "soup.traj"))

    env = dict(os.environ,
               SRNN_SETUPS_PLATFORM="cpu", JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    # --no-pipeline pins the pre-kill checkpoints synchronous so a
    # checkpoint deterministically survives the SIGKILL (under the async
    # pipeline the kill can race the background save on a fast host);
    # streams/checkpoints are bit-identical across the two modes (PR 3),
    # so the resumed run — default pipelined — still matches the oracle.
    proc = subprocess.run(
        [sys.executable, "-m", "srnn_tpu.setups", "mega_soup", "--smoke",
         "--root", str(tmp_path / "killed"), "--capture-every", "1",
         "--no-pipeline", "--chaos", "sigkill@4"],
        env=env, capture_output=True, timeout=240)
    assert proc.returncode == -9, proc.stderr.decode(errors="replace")

    d = glob.glob(str(tmp_path / "killed" / "exp-*"))[0]
    newest = latest_checkpoint(d)  # whatever survived the kill
    d_resumed = REGISTRY["mega_soup"](["--smoke", "--resume", d])
    assert d_resumed == d
    log = open(os.path.join(d, "log.txt")).read()
    assert f"resumed from {os.path.basename(newest)}" in log
    got = read_store(os.path.join(d, "soup.traj"))
    assert got["generations"].tolist() == want["generations"].tolist()
    np.testing.assert_array_equal(got["weights"], want["weights"])
    np.testing.assert_array_equal(got["uids"], want["uids"])
    final = restore_checkpoint(os.path.join(d, "ckpt-gen00000006"))
    oracle_final = restore_checkpoint(
        os.path.join(oracle, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(final.weights),
                                  np.asarray(oracle_final.weights))
