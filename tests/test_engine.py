"""Engine tests: vectorized fixpoint runs reproduce the reference's
qualitative distributions (BASELINE.md) at reduced trial counts."""

import jax
import jax.numpy as jnp
import numpy as np

from srnn_tpu import Topology, init_population
from srnn_tpu.engine import (
    classify_batch,
    fixpoint_density,
    run_fixpoint,
    run_known_fixpoint_variation,
    run_mixed_fixpoint,
)
from srnn_tpu.ops.predicates import CLS_DIVERGENT, CLS_FIX_OTHER, CLS_FIX_ZERO
from tests.test_apply import WW, AGG, RNN, identity_fixpoint_flat


def test_run_fixpoint_ww_distribution():
    # BASELINE: WW 23 divergent / 27 fix_zero of 50 — everything diverges or zeroes
    pop = init_population(WW, jax.random.key(0), 30)
    res = run_fixpoint(WW, pop, step_limit=100)
    counts = res.counts.tolist()
    assert counts[CLS_DIVERGENT] + counts[CLS_FIX_ZERO] == 30
    assert counts[CLS_DIVERGENT] > 0 and counts[CLS_FIX_ZERO] > 0


def test_run_fixpoint_rnn_mostly_diverges():
    # BASELINE: RNN 46 divergent / 4 fix_zero of 50
    pop = init_population(RNN, jax.random.key(1), 20)
    res = run_fixpoint(RNN, pop, step_limit=100)
    assert res.counts[CLS_DIVERGENT] > res.counts[CLS_FIX_ZERO]


def test_run_fixpoint_freezes_retired_trials():
    ident = jnp.asarray(identity_fixpoint_flat())
    pop = jnp.stack([ident, jnp.zeros(14)])
    res = run_fixpoint(WW, pop, step_limit=50)
    # both are fixpoints from step 0: no steps taken, weights unchanged
    assert res.steps.tolist() == [0, 0]
    np.testing.assert_array_equal(np.asarray(res.weights), np.asarray(pop))
    assert res.classes.tolist() == [CLS_FIX_OTHER, CLS_FIX_ZERO]


def test_run_fixpoint_trajectory_recording():
    pop = init_population(WW, jax.random.key(2), 4)
    res = run_fixpoint(WW, pop, step_limit=10, record=True)
    assert res.trajectory.shape == (11, 4, 14)
    np.testing.assert_array_equal(np.asarray(res.trajectory[0]), np.asarray(pop))


def test_mixed_fixpoint_training_rescues_ww():
    """mixed-self-fixpoints.py headline: enough training between attacks
    pushes WW fixpoint rate toward 1.0 (BASELINE: 0.2 -> 1.0)."""
    pop = init_population(WW, jax.random.key(3), 6)
    res_none = run_mixed_fixpoint(WW, pop, trains_per_application=0, step_limit=4)
    res_many = run_mixed_fixpoint(WW, pop, trains_per_application=300, step_limit=4)
    fixed_none = int(res_none.counts[CLS_FIX_ZERO] + res_none.counts[CLS_FIX_OTHER])
    fixed_many = int(res_many.counts[CLS_FIX_ZERO] + res_many.counts[CLS_FIX_OTHER])
    assert fixed_many > fixed_none
    assert int(res_many.counts[CLS_FIX_OTHER]) > 0  # non-trivial fixpoints


def test_known_fixpoint_variation_scale_monotonicity():
    """known-fixpoint-variation: smaller perturbations survive longer
    (BASELINE: 3.63 steps @1e0 -> 26.45 @1e-9).

    Note: the reference script *appears* to use sigmoid but its
    ``with_keras_params`` call never rebuilds the model, so the effective
    activation is linear (SURVEY quirk 2.4.11) — we test the effective
    behavior."""
    topo = WW
    ident = jnp.asarray(identity_fixpoint_flat())
    key = jax.random.key(4)
    results = {}
    for scale in (1.0, 1e-6):
        ks = jax.random.split(key, 20)
        pert = jax.vmap(
            lambda k: ident + jax.random.uniform(k, ident.shape, minval=-scale, maxval=scale)
        )(ks)
        res = run_known_fixpoint_variation(topo, pert, max_steps=50)
        results[scale] = float(res.time_to_vergence.mean())
    assert results[1e-6] > results[1.0]


def test_fixpoint_density_immediate_classification():
    """fixpoint-density.py: random inits classified with no dynamics —
    at eps=1e-4 virtually everything is 'other'."""
    pop = init_population(WW, jax.random.key(5), 1000)
    counts = fixpoint_density(WW, pop)
    assert int(counts.sum()) == 1000
    assert int(counts[4]) > 900  # 'other' dominates for untrained nets


def test_classify_batch_matches_scalar_classify():
    pop = jnp.stack([jnp.asarray(identity_fixpoint_flat()), jnp.zeros(14)])
    ids = classify_batch(WW, pop)
    assert ids.tolist() == [CLS_FIX_OTHER, CLS_FIX_ZERO]


def test_run_training_shuffle_key():
    """run_training(shuffle_key=...) emulates keras fit's default per-epoch
    sample shuffle (established by the golden replay of the 2019
    artifacts): it must CHANGE the weightwise outcome per-step (14 samples
    per epoch, order matters for sequential SGD), be a bitwise NO-OP for
    the recurrent variant (single-sequence sample set), and leave the
    weightwise training attractor class distribution intact."""
    from srnn_tpu.engine import run_training

    pop_ww = init_population(WW, jax.random.key(11), 16)
    plain = run_training(WW, pop_ww, epochs=60, epsilon=1e-4)
    shuf = run_training(WW, pop_ww, epochs=60, epsilon=1e-4,
                        shuffle_key=jax.random.key(0))
    assert not np.array_equal(np.asarray(plain.weights),
                              np.asarray(shuf.weights))
    # the science outcome survives the order change: training drives WW
    # toward fixpoints either way (training-fixpoints.py headline)
    assert int(shuf.counts[CLS_DIVERGENT]) == 0
    assert shuf.counts.tolist() == plain.counts.tolist()

    # single-sample epochs (the whole sequence for RNN, the aggregate
    # vector for AGG): permuting one sample is the identity -> bitwise
    pop_rnn = init_population(RNN, jax.random.key(12), 4) * 0.2
    plain_r = run_training(RNN, pop_rnn, epochs=5, epsilon=1e-4)
    shuf_r = run_training(RNN, pop_rnn, epochs=5, epsilon=1e-4,
                          shuffle_key=jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(plain_r.weights),
                                  np.asarray(shuf_r.weights))
    pop_agg = init_population(AGG, jax.random.key(13), 4)
    plain_a = run_training(AGG, pop_agg, epochs=5, epsilon=1e-4)
    shuf_a = run_training(AGG, pop_agg, epochs=5, epsilon=1e-4,
                          shuffle_key=jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(plain_a.weights),
                                  np.asarray(shuf_a.weights))
