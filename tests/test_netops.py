"""Reference API-surface operators, multihost mesh construction, and the
attractor example script."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology, apply_to_weights, init_flat
from srnn_tpu.fixtures import identity_fixpoint_flat
from srnn_tpu.netops import (absorb, are_weights_within, attack, fuck, meet,
                             self_attack, weights_to_string)

TOPO = Topology("weightwise", width=2, depth=2)


def test_attack_fuck_meet_are_applications():
    a = init_flat(TOPO, jax.random.key(0)) * 0.5
    b = init_flat(TOPO, jax.random.key(1)) * 0.5
    expected = np.asarray(apply_to_weights(TOPO, a, b))
    np.testing.assert_array_equal(np.asarray(attack(TOPO, a, b)), expected)
    np.testing.assert_array_equal(np.asarray(fuck(TOPO, a, b)), expected)
    np.testing.assert_array_equal(np.asarray(absorb(TOPO, a, b)), expected)
    np.testing.assert_array_equal(np.asarray(meet(TOPO, a, b)), expected)


def test_self_attack_iterates_on_updated_weights():
    w = init_flat(TOPO, jax.random.key(2)) * 0.5
    once = apply_to_weights(TOPO, w, w)
    twice = apply_to_weights(TOPO, once, once)  # net updates between rounds
    np.testing.assert_allclose(
        np.asarray(self_attack(TOPO, w, iterations=2)), np.asarray(twice),
        rtol=1e-6)


def test_identity_is_self_attack_fixed():
    fp = identity_fixpoint_flat(TOPO)
    np.testing.assert_allclose(
        np.asarray(self_attack(TOPO, fp, iterations=5)), np.asarray(fp),
        atol=1e-6)


def test_are_weights_within():
    assert bool(are_weights_within(jnp.asarray([0.1, -0.2]), -0.2, 0.1))
    assert not bool(are_weights_within(jnp.asarray([0.1, -0.21]), -0.2, 0.1))


def test_weights_to_string_layout():
    s = weights_to_string(TOPO, identity_fixpoint_flat(TOPO))
    blocks = s.split("\n\n")
    assert len(blocks) == 3                      # three kernels
    assert blocks[0].count("\n") == 3            # (4, 2) kernel: 4 rows
    assert "1.0000000" in blocks[0]


def test_multislice_mesh_axes():
    from srnn_tpu.parallel import DCN_AXIS, multislice_soup_mesh

    mesh = multislice_soup_mesh(2)
    assert mesh.axis_names == (DCN_AXIS, "soup")
    assert mesh.devices.shape == (2, len(jax.devices()) // 2)
    with pytest.raises(ValueError, match="split"):
        multislice_soup_mesh(3)


_EXAMPLES_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


@pytest.mark.slow
def test_round5_examples_smoke():
    """The analysis examples run headless at smoke scale (figures skipped —
    the committed PNGs are full-sample renders)."""
    sys.path.insert(0, _EXAMPLES_DIR)
    import mixed_attack_sweep
    import natural_cycles

    # tiny stream prefix: exercises both the hit path (RUN_BATCH finds a
    # handful) and all verification arithmetic; a broken stream rescan
    # would surface as zero hits
    hits = natural_cycles.main(["--samples", "500000", "--no-figure",
                                "--basin-trials", "200"])
    assert hits and hits > 0
    rows = mixed_attack_sweep.main(
        ["--per-type", "24", "--generations", "3", "--no-figure"])
    assert len(rows) == len(mixed_attack_sweep.RATES)
    for r in rows:
        for name in mixed_attack_sweep.TYPE_NAMES:
            assert sum(r["counts"][name]) == 24


def test_attractor_examples_run():
    sys.path.insert(0, _EXAMPLES_DIR)
    import attractors

    assert attractors.single_point_training(steps=200) < 1e-3
    counts = attractors.random_nets_converge(trials=16)
    assert counts.sum() == 16
    a, b = attractors.two_net_cycle(steps=5)
    assert a.shape == (14,)
    drift0, drift = attractors.offset_perturbation(scale=1e-6, steps=10)
    assert drift0 > 0
    # cycle themes (notebook cells 20-23): bias-free linear cycle decays
    # to 0; a constant offset moves the attractor off zero and both starts
    # land on the SAME point (it is a property of the composed map)
    finals = attractors.network_cycle_trajectories(steps=60, starts=2)
    assert all(np.abs(f).max() < 1e-3 for f in finals)
    off = attractors.network_cycle_trajectories(steps=60, starts=2,
                                                offset=0.1)
    assert np.abs(off[0]).max() > 1e-3
    np.testing.assert_allclose(off[0], off[1], atol=1e-5)
    # basin sweep: tiny perturbations keep the fixpoint, huge ones lose it
    rows = attractors.basin_of_attraction(
        scales=(1e-8, 1e0), trials=8, steps=10)
    assert rows[0][1] == 1.0 and rows[-1][1] < 1.0
