"""Block autotuner (``srnn_tpu.autotune``).

The autotuner only ever changes a TILE SIZE, so every claim splits in
two: (1) the machinery — deterministic grid walk under
``SRNN_AUTOTUNE_FIXED=1``, ``tuning.json`` round-trip with memo-hit on
restart, corrupt-file graceful skip, roofline-vs-min-wall judgment —
and (2) the oracle — a mega run with the autotuner on is BITWISE
identical to the same run under ``--no-autotune``.
"""

import json
import os

import numpy as np
import pytest

from srnn_tpu import autotune
from srnn_tpu.setups import REGISTRY
from srnn_tpu.topology import Topology
from srnn_tpu.utils import aot

WW = Topology("weightwise", width=2, depth=2)


@pytest.fixture
def tuning_dir(tmp_path, monkeypatch):
    """Isolate tuning.json (and the executable cache it lives next to)
    in tmp_path, with a clean in-memory memo before and after."""
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("SRNN_COMPILE_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(aot, "_cache_dir_enabled", None)
    autotune.reset_for_tests()
    yield tmp_path
    autotune.reset_for_tests()


@pytest.fixture
def fixed_mode(monkeypatch):
    """Synthetic-wall mode: the grid walk runs no jax work and is
    byte-reproducible (smallest candidate wins via min-wall)."""
    monkeypatch.setenv(autotune.FIXED_ENV, "1")


def test_fixed_grid_is_deterministic(tuning_dir, fixed_mode):
    """Two tunes of the same key from scratch write byte-identical
    tuning.json files (the grid walk, judgment and persistence carry no
    timing jitter under SRNN_AUTOTUNE_FIXED=1)."""
    path = os.path.join(str(tuning_dir), autotune.TUNING_NAME)

    e1 = autotune.autotune_generation(WW, 512)
    assert e1 is not None and e1["judged_by"] == "min_wall"
    assert e1["block"] == min(autotune.GENERATION_CANDIDATES)
    first = open(path, "rb").read()

    os.remove(path)
    autotune.reset_for_tests()
    e2 = autotune.autotune_generation(WW, 512)
    assert e2["block"] == e1["block"]
    assert open(path, "rb").read() == first


def test_roundtrip_memo_hits_without_remeasuring(tuning_dir, fixed_mode):
    """A restart (fresh memo) serves the persisted winner from
    tuning.json — lookup is a pure table read, zero new measurements."""
    e = autotune.autotune_generation(WW, 512)
    assert autotune._measured_keys  # this process measured

    autotune.reset_for_tests()     # "restart"
    got = autotune.lookup("generation", WW.variant, 512, WW.num_weights,
                          dtype="float32")
    assert got == e["block"]
    assert not autotune._measured_keys  # served from disk, not re-measured
    # and the tuning entry round-tripped its full report
    raw = json.load(open(os.path.join(str(tuning_dir),
                                      autotune.TUNING_NAME)))
    assert raw["version"] == autotune.SCHEMA_VERSION
    (entry,) = raw["entries"].values()
    assert entry["walls_s"] and entry["candidates"]


def test_corrupt_tuning_file_is_skipped_then_overwritten(tuning_dir,
                                                         fixed_mode):
    """A torn/garbage tuning.json must never crash: lookups see an empty
    table, and the next tune atomically replaces the file."""
    path = os.path.join(str(tuning_dir), autotune.TUNING_NAME)
    open(path, "w").write('{"version": 1, "entries": ')  # torn write
    assert autotune.lookup("generation", WW.variant, 512,
                           WW.num_weights) is None

    autotune.autotune_generation(WW, 512)
    raw = json.load(open(path))  # valid again
    assert raw["entries"]


def test_judge_roofline_and_min_wall_fallback():
    """Judgment ranks by achieved flops/wall when the ledger reports
    flops (a slower wall can still win on a bigger program), and falls
    back to min wall when it doesn't."""
    walls = {256: 1.0, 512: 2.0}
    winner, report = autotune._judge(walls, {256: 100.0, 512: 400.0})
    assert winner == 512 and report["judged_by"] == "roofline"
    assert report["roofline_fraction"]["512"] == 1.0

    winner, report = autotune._judge(walls, {256: None, 512: None})
    assert winner == 256 and report["judged_by"] == "min_wall"


def test_disabled_env_blocks_lookup_and_measurement(tuning_dir, fixed_mode,
                                                    monkeypatch):
    """SRNN_NO_AUTOTUNE=1 is the A/B oracle switch: no reads, no writes,
    no measurements."""
    autotune.autotune_generation(WW, 512)  # persist a winner first
    autotune.reset_for_tests()
    monkeypatch.setenv(autotune.DISABLE_ENV, "1")
    assert not autotune.enabled()
    assert autotune.tuning_path() is None
    assert autotune.lookup("generation", WW.variant, 512,
                           WW.num_weights) is None
    assert autotune.autotune_generation(WW, 512) is None


def _mega_flags(root):
    return ["--smoke", "--root", str(root), "--layout", "popmajor",
            "--generation-impl", "fused"]


@pytest.mark.slow
def test_no_autotune_bitwise_ab_mega_soup(tuning_dir, fixed_mode, tmp_path):
    """The oracle, end to end on the flagship loop: a fused mega_soup
    smoke with the autotuner active (tuned block resolved from
    tuning.json) finishes BITWISE identical to its --no-autotune twin —
    tuning changes tile sizes, never results.  slow lane (subprocess-
    class acceptance e2e, like the kill9/fleet oracles); the tier-1
    unit tests above plus the autotune_smoke CI group keep the fast
    lane covered."""
    from srnn_tpu.experiment import restore_checkpoint

    d_tuned = REGISTRY["mega_soup"](_mega_flags(tmp_path / "tuned"))
    assert os.path.exists(os.path.join(str(tuning_dir),
                                       autotune.TUNING_NAME))
    d_plain = REGISTRY["mega_soup"](
        _mega_flags(tmp_path / "plain") + ["--no-autotune"])

    want = restore_checkpoint(os.path.join(d_tuned, "ckpt-gen00000006"))
    got = restore_checkpoint(os.path.join(d_plain, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(want.weights),
                                  np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(want.uids),
                                  np.asarray(got.uids))
    assert int(want.next_uid) == int(got.next_uid)


@pytest.mark.slow
def test_no_autotune_bitwise_ab_mega_multisoup(tuning_dir, fixed_mode,
                                               tmp_path):
    """Same oracle on the heterogeneous loop (per-type tuning keys)."""
    from srnn_tpu.experiment import restore_multi_checkpoint

    d_tuned = REGISTRY["mega_multisoup"](_mega_flags(tmp_path / "tuned"))
    d_plain = REGISTRY["mega_multisoup"](
        _mega_flags(tmp_path / "plain") + ["--no-autotune"])

    want = restore_multi_checkpoint(os.path.join(d_tuned,
                                                 "ckpt-gen00000006"))
    got = restore_multi_checkpoint(os.path.join(d_plain,
                                                "ckpt-gen00000006"))
    for t in range(len(want.weights)):
        np.testing.assert_array_equal(np.asarray(want.weights[t]),
                                      np.asarray(got.weights[t]))
        np.testing.assert_array_equal(np.asarray(want.uids[t]),
                                      np.asarray(got.uids[t]))
