import functools

import jax
import jax.numpy as jnp
import numpy as np

from srnn_tpu import (Topology, apply_to_weights, classify, init_population,
                      is_diverged, is_fixpoint, is_zero)
from srnn_tpu.ops.predicates import (
    CLS_DIVERGENT,
    CLS_FIX_OTHER,
    CLS_FIX_SEC,
    CLS_FIX_ZERO,
    CLS_OTHER,
    count_classes,
)
from tests.test_apply import WW, identity_fixpoint_flat


def self_apply(topo, flat_self):
    return functools.partial(apply_to_weights, topo, flat_self)


def test_is_diverged():
    w = jnp.ones(14)
    assert not bool(is_diverged(w))
    assert bool(is_diverged(w.at[3].set(jnp.nan)))
    assert bool(is_diverged(w.at[3].set(jnp.inf)))
    assert bool(is_diverged(w.at[3].set(-jnp.inf)))


def test_is_zero_inclusive_bounds():
    eps = 1e-4
    w = jnp.full(14, eps)  # exactly eps is still "zero" (<= bound)
    assert bool(is_zero(w, eps))
    assert not bool(is_zero(w.at[0].set(eps * 1.01), eps))
    assert not bool(is_zero(w.at[0].set(jnp.nan), eps))


def test_identity_is_fixpoint():
    w = jnp.asarray(identity_fixpoint_flat())
    f = self_apply(WW, w)
    assert bool(is_fixpoint(f, w))
    assert bool(is_fixpoint(f, w, degree=2))


def test_is_fixpoint_strict_epsilon():
    # zero weights under linear WW map to exactly zero -> fixpoint
    w = jnp.zeros(14)
    f = self_apply(WW, w)
    assert bool(is_fixpoint(f, w, epsilon=1e-10))


def test_classify_basic_classes():
    eps = 1e-4
    ident = jnp.asarray(identity_fixpoint_flat())
    assert int(classify(self_apply(WW, ident), ident, eps)) == CLS_FIX_OTHER

    zero = jnp.zeros(14)
    assert int(classify(self_apply(WW, zero), zero, eps)) == CLS_FIX_ZERO

    nanw = zero.at[0].set(jnp.nan)
    assert int(classify(self_apply(WW, nanw), nanw, eps)) == CLS_DIVERGENT

    # a generic random net is almost surely not a fixpoint
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=14).astype(np.float32))
    assert int(classify(self_apply(WW, w), w, eps)) in (CLS_OTHER, CLS_DIVERGENT)


def test_gain_minus_one_nets_are_universal_two_cycles():
    """The closed-form law behind the 100M-sample density result
    (RESULTS.md / examples/natural_cycles.py): the linear weightwise
    transform is affine in its target, f_w(v) = a(w) v + g(w), so any net
    whose composed input gain a(w) = W1[0,:] @ W2 @ W3 equals -1 is an
    involution — classify must call it fix_sec (a 2-cycle, never a
    degree-1 fixpoint)."""
    from srnn_tpu.ops.flatten import unflatten

    rng = np.random.default_rng(5)
    for _ in range(5):
        w = rng.normal(size=WW.num_weights, scale=0.6)
        mats = [np.asarray(m) for m in unflatten(WW, jnp.asarray(w))]
        c = mats[0][0:1]
        for m in mats[1:-1]:
            c = c @ m  # partial path sum up to the last kernel
        # solve c @ W_last = -1 exactly for the last (w, 1) kernel
        w[WW.offsets[-2]:] = (-c / (c @ c.T)).ravel()
        flat = jnp.asarray(w.astype(np.float32))
        assert int(classify(self_apply(WW, flat), flat, 1e-4)) == CLS_FIX_SEC


def test_transform_target_jacobian_structure():
    """Structural linear algebra of every transform's TARGET dependence
    (linear activation), the facts the round-5 density/cycle analysis
    rests on (RESULTS.md):

      * weightwise: J = a(w)·I — one scalar gain times identity;
      * aggregating: rank <= min(aggregates, width) (the MLP bottleneck
        caps the replicate∘MLP∘segment-avg map);
      * fft (reference quirk, fft_use_target=False): J = 0 — the
        transform ignores its target entirely (network.py:494-499);
      * fft_use_target=True: same bottleneck bound as aggregating;
      * recurrent: lower-triangular (causal — output t depends only on
        inputs <= t).
    """
    key = jax.random.key(3)
    for topo, check in [
        (Topology("weightwise"), "aI"),
        (Topology("aggregating"), "rank"),
        (Topology("fft"), "zero"),
        (Topology("fft", fft_use_target=True), "rank"),
        (Topology("recurrent"), "tril"),
    ]:
        w = init_population(topo, key, 1)[0] * 0.5
        p = topo.num_weights
        J = np.asarray(jax.jacfwd(
            lambda v: apply_to_weights(topo, w, v))(jnp.zeros(p)))
        if check == "aI":
            np.testing.assert_allclose(J, J[0, 0] * np.eye(p), atol=1e-7)
        elif check == "zero":
            np.testing.assert_allclose(J, 0.0, atol=1e-9)
        elif check == "rank":
            bound = min(topo.aggregates, topo.width)
            assert np.linalg.matrix_rank(J, tol=1e-6) <= bound
        else:  # tril
            np.testing.assert_allclose(J, np.tril(J), atol=1e-7)


def test_classify_vmapped_and_counts():
    ident = jnp.asarray(identity_fixpoint_flat())
    pop = jnp.stack([ident, jnp.zeros(14), jnp.full(14, jnp.nan)])

    def cls(w):
        return classify(self_apply(WW, w), w, 1e-4)

    ids = jax.vmap(cls)(pop)
    assert ids.tolist() == [CLS_FIX_OTHER, CLS_FIX_ZERO, CLS_DIVERGENT]
    counts = count_classes(ids)
    assert counts.tolist() == [1, 1, 1, 0, 0]
