"""Golden tests for the four self-application transforms against hand-rolled
numpy implementations of the reference semantics (network.py:265-279, 359-386,
494-516, 544-564)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology, apply_to_weights, init_flat
from srnn_tpu.nets import aggregating, fft, recurrent, weightwise
from srnn_tpu.ops.flatten import flatten_mats, unflatten
from srnn_tpu.topology import aggregation_segments, normalized_weight_coords

WW = Topology("weightwise", width=2, depth=2)


def identity_fixpoint_flat():
    """The analytically-known identity fixpoint for the linear weightwise net
    (known-fixpoint-variation.py:20-25): kernels [[1,0],...] selecting the
    weight feature straight through."""
    mats = [
        np.array([[1.0, 0.0], [0, 0], [0, 0], [0, 0]], np.float32),
        np.array([[1.0, 0.0], [0, 0]], np.float32),
        np.array([[1.0], [0.0]], np.float32),
    ]
    return np.concatenate([m.ravel() for m in mats])


def np_mlp(mats, x, act=lambda v: v):
    h = x
    for m in mats:
        h = act(h @ m)
    return h


# ---------------------------------------------------------------- weightwise

def test_ww_identity_is_exact_fixpoint():
    w = jnp.asarray(identity_fixpoint_flat())
    out = apply_to_weights(WW, w, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(w), atol=0)


def test_ww_identity_maps_any_target_to_itself():
    w = jnp.asarray(identity_fixpoint_flat())
    tgt = jnp.asarray(np.random.default_rng(0).normal(size=14).astype(np.float32))
    out = apply_to_weights(WW, w, tgt)
    np.testing.assert_allclose(np.asarray(out), np.asarray(tgt), rtol=1e-6)


def test_ww_apply_matches_numpy_reference():
    rng = np.random.default_rng(1)
    self_flat = rng.normal(size=14).astype(np.float32)
    target = rng.normal(size=14).astype(np.float32)
    coords = normalized_weight_coords(WW)
    x = np.concatenate([target[:, None], coords], axis=1)
    mats = [np.asarray(m) for m in unflatten(WW, jnp.asarray(self_flat))]
    expected = np_mlp(mats, x)[:, 0]
    got = apply_to_weights(WW, jnp.asarray(self_flat), jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5)


def test_ww_apply_sigmoid():
    topo = WW.with_(activation="sigmoid")
    rng = np.random.default_rng(2)
    self_flat = rng.normal(size=14).astype(np.float32)
    target = rng.normal(size=14).astype(np.float32)
    coords = normalized_weight_coords(topo)
    x = np.concatenate([target[:, None], coords], axis=1)
    mats = [np.asarray(m) for m in unflatten(topo, jnp.asarray(self_flat))]
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    expected = np_mlp(mats, x, sig)[:, 0]
    got = apply_to_weights(topo, jnp.asarray(self_flat), jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5)


# --------------------------------------------------------------- aggregating

AGG = Topology("aggregating", width=2, depth=2, aggregates=4)


def test_agg_apply_matches_numpy_reference():
    rng = np.random.default_rng(3)
    p = AGG.num_weights
    self_flat = rng.normal(size=p).astype(np.float32)
    target = rng.normal(size=p).astype(np.float32)
    seg, counts = aggregation_segments(AGG)
    aggs = np.array([target[seg == s].mean() for s in range(4)], np.float32)
    mats = [np.asarray(m) for m in unflatten(AGG, jnp.asarray(self_flat))]
    new_aggs = np_mlp(mats, aggs[None, :])[0]
    expected = new_aggs[seg]
    got = apply_to_weights(AGG, jnp.asarray(self_flat), jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5)


def test_agg_leftovers_go_to_last_collection():
    topo = Topology("aggregating", width=2, depth=2, aggregates=3)  # P=16
    rng = np.random.default_rng(4)
    target = rng.normal(size=16).astype(np.float32)
    aggs = aggregating.aggregate(topo, jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(aggs)[2], target[10:].mean(), rtol=1e-6)


def test_agg_max_aggregators():
    topo = AGG.with_(aggregator="max")
    vals = np.arange(20, dtype=np.float32) - 10.0
    aggs = aggregating.aggregate(topo, jnp.asarray(vals))
    np.testing.assert_allclose(np.asarray(aggs), [-6, -1, 4, 9])

    # buggy max: a zero candidate never replaces the running max
    topo_b = AGG.with_(aggregator="max_buggy")
    vals = np.full(20, -5.0, np.float32)
    vals[7] = 0.0  # true max of collection 1 is 0.0 but starts at -5
    aggs_true = aggregating.aggregate(topo, jnp.asarray(vals))
    aggs_bug = aggregating.aggregate(topo_b, jnp.asarray(vals))
    assert np.asarray(aggs_true)[1] == 0.0
    assert np.asarray(aggs_bug)[1] == -5.0


def test_agg_shuffle_random_is_permutation():
    topo = AGG.with_(shuffler="random")
    rng = np.random.default_rng(5)
    self_flat = jnp.asarray(rng.normal(size=20).astype(np.float32))
    target = jnp.asarray(rng.normal(size=20).astype(np.float32))
    base = apply_to_weights(AGG, self_flat, target)
    shuf = apply_to_weights(topo, self_flat, target, key=jax.random.key(0))
    assert sorted(np.asarray(base).tolist()) == pytest.approx(
        sorted(np.asarray(shuf).tolist()))


# ----------------------------------------------------------------------- fft

FFT = Topology("fft", width=2, depth=2, aggregates=4)


def test_fft_apply_matches_numpy_reference():
    rng = np.random.default_rng(6)
    p = FFT.num_weights
    self_flat = rng.normal(size=p).astype(np.float32)
    target = rng.normal(size=p).astype(np.float32)
    coeffs = np.fft.fft(self_flat, n=4).real.astype(np.float32)  # quirk: self, not target
    mats = [np.asarray(m) for m in unflatten(FFT, jnp.asarray(self_flat))]
    new_coeffs = np_mlp(mats, coeffs[None, :])[0]
    expected = np.fft.ifft(new_coeffs, n=p).real
    got = apply_to_weights(FFT, jnp.asarray(self_flat), jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-6)


def test_fft_quirk_ignores_target_by_default():
    rng = np.random.default_rng(7)
    p = FFT.num_weights
    self_flat = jnp.asarray(rng.normal(size=p).astype(np.float32))
    t1 = jnp.asarray(rng.normal(size=p).astype(np.float32))
    t2 = jnp.asarray(rng.normal(size=p).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(apply_to_weights(FFT, self_flat, t1)),
        np.asarray(apply_to_weights(FFT, self_flat, t2)))
    fixed = FFT.with_(fft_use_target=True)
    assert not np.allclose(
        np.asarray(apply_to_weights(fixed, self_flat, t1)),
        np.asarray(apply_to_weights(fixed, self_flat, t2)))


def test_fft_rfft_mode_matches_numpy_reference():
    """fft_mode='rfft' — the EP prototype's real-input reduction
    (related/EP/src/FeatureReduction.py): first k rfft bins in, irfft out."""
    topo = FFT.with_(fft_mode="rfft")
    rng = np.random.default_rng(17)
    p = topo.num_weights
    self_flat = rng.normal(size=p).astype(np.float32)
    target = rng.normal(size=p).astype(np.float32)
    coeffs = np.fft.rfft(self_flat).real.astype(np.float32)[:4]
    mats = [np.asarray(m) for m in unflatten(topo, jnp.asarray(self_flat))]
    new_coeffs = np_mlp(mats, coeffs[None, :])[0]
    expected = np.fft.irfft(new_coeffs, n=p)
    got = apply_to_weights(topo, jnp.asarray(self_flat), jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-6)
    assert np.asarray(got).dtype == np.float32


# ----------------------------------------------------------------- recurrent

RNN = Topology("recurrent", width=2, depth=2)


def np_rnn(mats, dims, seq, act=lambda v: v):
    x = seq
    for layer, (_, units) in enumerate(dims):
        k, r = np.asarray(mats[2 * layer]), np.asarray(mats[2 * layer + 1])
        h = np.zeros(units, dtype=seq.dtype)
        outs = []
        for t in range(x.shape[0]):
            h = act(x[t] @ k + h @ r)
            outs.append(h)
        x = np.stack(outs)
    return x


def test_rnn_apply_matches_numpy_reference():
    rng = np.random.default_rng(8)
    p = RNN.num_weights
    self_flat = rng.normal(size=p).astype(np.float32) * 0.3
    target = rng.normal(size=p).astype(np.float32)
    mats = unflatten(RNN, jnp.asarray(self_flat))
    expected = np_rnn(mats, RNN.rnn_layer_dims, target[:, None])[:, 0]
    got = apply_to_weights(RNN, jnp.asarray(self_flat), jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-6)


def test_rnn_apply_tanh():
    topo = RNN.with_(activation="tanh")
    rng = np.random.default_rng(9)
    p = topo.num_weights
    self_flat = rng.normal(size=p).astype(np.float32) * 0.3
    target = rng.normal(size=p).astype(np.float32)
    mats = unflatten(topo, jnp.asarray(self_flat))
    expected = np_rnn(mats, topo.rnn_layer_dims, target[:, None], np.tanh)[:, 0]
    got = apply_to_weights(topo, jnp.asarray(self_flat), jnp.asarray(target))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("width,depth", [(2, 2), (3, 1), (4, 3)])
def test_rnn_associative_scan_matches_sequential(width, depth):
    """rnn_scan='associative' (affine associative_scan, O(log T) depth) is
    the same map as the serial lax.scan for the linear activation."""
    topo = Topology("recurrent", width=width, depth=depth)
    fast = topo.with_(rnn_scan="associative")
    rng = np.random.default_rng(10)
    p = topo.num_weights
    self_flat = jnp.asarray(rng.normal(size=p).astype(np.float32) * 0.3)
    target = jnp.asarray(rng.normal(size=p).astype(np.float32))
    seq = apply_to_weights(topo, self_flat, target)
    assoc = apply_to_weights(fast, self_flat, target)
    np.testing.assert_allclose(np.asarray(assoc), np.asarray(seq),
                               rtol=1e-5, atol=1e-6)


def test_rnn_associative_requires_linear():
    with pytest.raises(ValueError, match="associative"):
        Topology("recurrent", activation="tanh", rnn_scan="associative")


def test_init_population_chunked_matches_direct():
    """The lax.map chunking at mega-population sizes (QR VMEM workaround)
    produces the same particles as the direct vmap."""
    import srnn_tpu.init as init_mod

    topo = Topology("recurrent", width=2, depth=2)
    key = jax.random.key(7)
    direct = init_mod.init_population(topo, key, 10)
    old = init_mod._INIT_CHUNK
    init_mod._INIT_CHUNK = 4  # force chunked path: 2 chunks + tail of 2
    try:
        chunked = init_mod.init_population(topo, key, 10)
    finally:
        init_mod._INIT_CHUNK = old
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(chunked))


# ------------------------------------------------------------------- generic

@pytest.mark.parametrize("topo", [WW, AGG, FFT, RNN])
def test_apply_is_jittable_and_vmappable(topo):
    n = 5
    keys = jax.random.split(jax.random.key(0), n)
    pop = jax.vmap(lambda k: init_flat(topo, k))(keys)
    fn = jax.jit(jax.vmap(lambda s: apply_to_weights(topo, s, s)))
    out = fn(pop)
    assert out.shape == (n, topo.num_weights)
    assert np.all(np.isfinite(np.asarray(out)))


@pytest.mark.parametrize("topo", [WW, AGG, FFT, RNN])
def test_init_shapes_and_finiteness(topo):
    flat = init_flat(topo, jax.random.key(1))
    assert flat.shape == (topo.num_weights,)
    assert np.all(np.isfinite(np.asarray(flat)))


def test_init_recurrent_kernels_orthogonal():
    topo = Topology("recurrent", width=8, depth=2)
    flat = init_flat(topo, jax.random.key(2))
    mats = unflatten(topo, flat)
    r = np.asarray(mats[1])  # first recurrent kernel (8,8)
    np.testing.assert_allclose(r @ r.T, np.eye(8), atol=1e-5)


def test_init_glorot_bounds():
    flat = np.asarray(init_flat(WW, jax.random.key(3)))
    mats = unflatten(WW, jnp.asarray(flat))
    m0 = np.asarray(mats[0])  # (4,2): limit sqrt(6/6)=1
    assert np.all(np.abs(m0) <= 1.0)
