"""Continuous profiling plane (PR 20): the sampling profiler's fold
tables against a crafted busy thread, ring/table bounds, monotone
counter deltas, the utilization decomposition formula, anomaly-capture
fire-once + atomic publish + FIFO retention, the thread-dump schema,
``report --profile``'s no-data contract (exit 2), the Perfetto
utilization counter track, and the ``--no-profile`` bitwise A/B oracle
on both mega loops."""

import json
import os
import threading
import time
from collections import Counter

import numpy as np
import pytest

from srnn_tpu.setups import REGISTRY
from srnn_tpu.telemetry.metrics import MetricsRegistry
from srnn_tpu.telemetry.profiler import (AnomalyCapture, SamplingProfiler,
                                         capture_index, thread_dump,
                                         utilization_from_pipeline,
                                         update_utilization_gauges)
from srnn_tpu.utils.pipeline import spawn_thread


# ---------------------------------------------------------------------------
# the sampler: fold correctness, bounds, monotone gauges
# ---------------------------------------------------------------------------


def _busy_spin(stop):
    """A distinctively named hot loop the sampler must attribute."""
    while not stop.is_set():
        sum(i * i for i in range(200))


def test_sampler_folds_busy_thread():
    """A thread spinning in ``_busy_spin`` dominates its fold table, and
    the folded token is the fold-stable ``file.func`` form (no line
    numbers)."""
    stop = threading.Event()
    t = spawn_thread(_busy_spin, name="busy-test", args=(stop,))
    prof = SamplingProfiler(hz=200.0, ring_s=2.0)
    try:
        with prof:
            time.sleep(0.4)
    finally:
        stop.set()
        t.join(timeout=5.0)
    tables = prof.tables()
    assert "busy-test" in tables
    folded, n = max(tables["busy-test"].items(), key=lambda kv: kv[1])
    assert n >= 1
    assert "test_profiler._busy_spin" in folded
    assert ";" in folded          # root-first chain, not a single frame
    assert ":" not in folded      # no file:line churn in the fold key
    # the sampler never profiles itself
    assert SamplingProfiler.THREAD_NAME not in tables
    s = prof.stats()
    assert s["samples"] >= 10 and s["threads"] >= 1
    # stop() is idempotent and bounded
    prof.stop()


def test_sampler_ring_and_table_bounds():
    """The raw-sample ring holds exactly ``hz * ring_s`` ticks, and a
    fold table past ``max_stacks`` degrades into ``<overflow>`` instead
    of growing without bound."""
    prof = SamplingProfiler(hz=10.0, ring_s=1.0, max_stacks=16)
    # drive ticks synchronously — no sampler thread, no timing in play
    for _ in range(50):
        prof._sample_once(own_ident=-1)
    assert prof.samples == 50
    ring = prof.ring_tail()
    assert len(ring) == 10        # maxlen = int(10 * 1.0)
    assert all(set(r) == {"t", "stacks"} for r in ring)
    # prefill one thread's table to the bound: the next real fold drops
    name = threading.current_thread().name
    prof._tables[name] = Counter({f"synthetic;s{i}": 1 for i in range(16)})
    prof._sample_once(own_ident=-1)
    assert prof.stacks_dropped >= 1
    assert prof._tables[name]["<overflow>"] >= 1
    assert len(prof._tables[name]) == 17   # 16 distinct + <overflow>


def test_update_gauges_counters_advance_by_delta():
    """Repeated folds are monotone: two flushes of the same sampler
    state leave the counters at the true totals, not doubled."""
    prof = SamplingProfiler(hz=50.0, ring_s=1.0)
    for _ in range(7):
        prof._sample_once(own_ident=-1)
    reg = MetricsRegistry()
    prof.update_gauges(reg)
    prof.update_gauges(reg)       # second fold with no new ticks
    rows = reg.rows()
    assert rows["srnn_soup_profile_samples_total"] == 7
    assert rows["srnn_soup_profile_overruns_total"] == 0
    assert rows["srnn_soup_profile_stacks_dropped_total"] == 0
    assert rows["srnn_soup_profile_threads"] >= 1
    prof._sample_once(own_ident=-1)
    prof.update_gauges(reg)
    assert reg.rows()["srnn_soup_profile_samples_total"] == 8


def test_write_files_artifacts(tmp_path):
    """``write_files`` lands the folded exchange format and a jsonl
    stream whose first row is the meta row."""
    prof = SamplingProfiler(hz=50.0, ring_s=1.0)
    for _ in range(5):
        prof._sample_once(own_ident=-1)
    prof.write_files(str(tmp_path))
    folded = (tmp_path / "profile.folded").read_text().splitlines()
    assert folded
    for line in folded:
        stack, _, count = line.rpartition(" ")
        assert int(count) >= 1 and ";" in stack
    rows = [json.loads(x) for x in
            (tmp_path / "profile.jsonl").read_text().splitlines()]
    assert rows[0]["kind"] == "profile_meta" and rows[0]["samples"] == 5
    assert all({"thread", "stack", "count"} <= set(r) for r in rows[1:])


# ---------------------------------------------------------------------------
# thread dump
# ---------------------------------------------------------------------------


def test_thread_dump_schema():
    dump = thread_dump()
    assert set(dump) == {"t", "n_threads", "threads"}
    assert dump["n_threads"] == len(dump["threads"]) >= 1
    by_name = {d["name"]: d for d in dump["threads"]}
    me = by_name[threading.current_thread().name]
    assert set(me) == {"name", "ident", "daemon", "alive", "registered",
                       "stack"}
    assert me["alive"] is True
    # the dump keeps file:line (the fold tables deliberately do not)
    assert any("test_profiler.py:" in fr for fr in me["stack"])
    # sorted by name for a stable diffable artifact
    names = [d["name"] for d in dump["threads"]]
    assert names == sorted(names)


# ---------------------------------------------------------------------------
# utilization decomposition
# ---------------------------------------------------------------------------


def test_utilization_formula():
    u = utilization_from_pipeline(
        {"wall_s": 10.0, "device_wait_s": 4.0, "host_io_s": 3.0})
    assert u == {"device_busy": 0.4, "host_blocked": 0.3, "idle": 0.3}
    # host I/O hidden behind device compute never exceeds the gap
    u = utilization_from_pipeline(
        {"wall_s": 10.0, "device_wait_s": 8.0, "host_io_s": 5.0})
    assert u == {"device_busy": 0.8, "host_blocked": 0.2, "idle": 0.0}
    # degenerate chunks are all-zero, never NaN
    assert utilization_from_pipeline({"wall_s": 0.0}) == \
        {"device_busy": 0.0, "host_blocked": 0.0, "idle": 0.0}
    # fractions clamp even when the meter over-reports
    u = utilization_from_pipeline(
        {"wall_s": 1.0, "device_wait_s": 5.0, "host_io_s": 5.0})
    assert u["device_busy"] == 1.0 and u["host_blocked"] == 0.0
    assert u["idle"] == 0.0


def test_update_utilization_gauges():
    reg = MetricsRegistry()
    u = update_utilization_gauges(
        reg, {"wall_s": 10.0, "device_wait_s": 4.0, "host_io_s": 3.0})
    rows = reg.rows()
    assert rows["srnn_soup_utilization_device_busy"] == u["device_busy"]
    assert rows["srnn_soup_utilization_host_blocked"] == 0.3
    assert rows["srnn_soup_utilization_idle"] == 0.3


# ---------------------------------------------------------------------------
# anomaly capture: fire-once, atomic publish, FIFO retention
# ---------------------------------------------------------------------------


def _firing(rule, value=1.0):
    return {"rule": rule, "state": "firing", "value": value}


def test_capture_bundle_contents_and_fire_once(tmp_path):
    run = str(tmp_path)
    (tmp_path / "exemplars.jsonl").write_text(
        json.dumps({"kind": "exemplar", "lat_ms": 3.0}) + "\n")
    prof = SamplingProfiler(hz=50.0, ring_s=5.0)
    for _ in range(3):
        prof._sample_once(own_ident=-1)
    reg = MetricsRegistry()
    reg.gauge("soup_nan_frac", help="n").set(0.5)
    cap = AnomalyCapture(run, profiler=prof, registry=reg, max_bundles=4,
                         ring_s=5.0, device_trace=False)
    cap.on_transitions([_firing("soup_nan_frac")], generation=12)
    # a sustained condition latches upstream: later turns carry no
    # firing edge and must not re-capture
    cap.on_transitions([])
    cap.on_transitions([{"rule": "soup_nan_frac", "state": "cleared"}])
    bundles = sorted(os.listdir(tmp_path / "anomaly"))
    assert bundles == ["soup_nan_frac-0000"]   # no .tmp- residue either
    bdir = tmp_path / "anomaly" / "soup_nan_frac-0000"
    doc = json.loads((bdir / "capture.json").read_text())
    assert doc["rule"] == "soup_nan_frac" and doc["seq"] == 0
    assert doc["transition"]["state"] == "firing"
    assert doc["context"] == {"generation": 12}
    assert doc["profiler"]["samples"] == 3
    assert "backend" in doc and "errors" not in doc
    samples = [json.loads(x) for x in
               (bdir / "samples.jsonl").read_text().splitlines()]
    assert len(samples) == 3 and all("stacks" in r for r in samples)
    threads = json.loads((bdir / "threads.json").read_text())
    assert threads["n_threads"] >= 1
    metrics = json.loads((bdir / "metrics.json").read_text())
    assert metrics["srnn_soup_nan_frac"] == 0.5
    assert (bdir / "exemplars.jsonl").exists()
    assert reg.rows()[
        'srnn_soup_anomaly_captures_total{rule="soup_nan_frac"}'] == 1

    idx = capture_index(run)
    assert [e["name"] for e in idx] == ["soup_nan_frac-0000"]
    e = idx[0]
    assert e["rule"] == "soup_nan_frac" and e["seq"] == 0
    assert e["samples"] and e["threads"] and e["metrics"]
    assert e["exemplars"] and not e["trace"]


def test_capture_fifo_retention_and_seq_resume(tmp_path):
    run = str(tmp_path)
    cap = AnomalyCapture(run, max_bundles=2, device_trace=False)
    stamp = time.time() - 100
    for i, rule in enumerate(["a", "b", "c", "d"]):
        path = cap.capture(_firing(rule))
        # deterministic FIFO ordering regardless of fs mtime resolution
        os.utime(path, (stamp + i, stamp + i))
    names = sorted(os.listdir(tmp_path / "anomaly"))
    assert names == ["c-0002", "d-0003"]      # oldest two evicted
    # a restarted attempt never clobbers a published bundle: a fresh
    # capturer's seq bumps past any name collision
    cap2 = AnomalyCapture(run, max_bundles=4, device_trace=False)
    os.makedirs(tmp_path / "anomaly" / "d-0000")
    cap2.capture(_firing("d"))
    assert "d-0001" in os.listdir(tmp_path / "anomaly")
    assert not os.listdir(tmp_path / "anomaly" / "d-0000")   # untouched


def test_capture_is_fail_soft(tmp_path, capsys):
    """A broken capture must never take down the run: the hook eats the
    exception, counts it, and says so on stderr."""
    cap = AnomalyCapture(str(tmp_path / "missing" / "x" / "y"),
                         device_trace=False)
    cap.run_dir = "\0invalid"      # force an OSError inside capture()
    cap.on_transitions([_firing("soup_nan_frac")])
    assert cap.errors == 1 and cap.captures == []
    assert "anomaly capture failed" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# report --profile: render + the no-data contract
# ---------------------------------------------------------------------------


def test_report_profile_renders(tmp_path, capsys):
    from srnn_tpu.telemetry import report

    prof = SamplingProfiler(hz=50.0, ring_s=1.0)
    for _ in range(4):
        prof._sample_once(own_ident=-1)
    prof.write_files(str(tmp_path))
    reg = MetricsRegistry()
    update_utilization_gauges(
        reg, {"wall_s": 10.0, "device_wait_s": 4.0, "host_io_s": 3.0})
    reg.write_textfile(str(tmp_path / "metrics.prom"))
    AnomalyCapture(str(tmp_path), profiler=prof,
                   device_trace=False).capture(_firing("soup_nan_frac"))

    s = report.summarize_profile(str(tmp_path))
    assert not s["no_data"]
    assert s["meta"]["samples"] == 4
    assert s["utilization"] == {"device_busy": 0.4, "host_blocked": 0.3,
                                "idle": 0.3}
    thread = next(iter(s["top_stacks"]))
    top = s["top_stacks"][thread][0]
    assert top["count"] >= 1 and 0 < top["share"] <= 1

    assert report.main(["--profile", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "sampler: 50.0Hz, 4 samples" in out
    assert "device_busy=40.0%" in out
    assert "soup_nan_frac-0000" in out


def test_report_profile_no_data_exit2(tmp_path, capsys):
    """A --no-profile run dir must exit 2, never render an
    empty-but-valid profile an operator would misread as 'nothing was
    hot'."""
    from srnn_tpu.telemetry import report

    assert report.main(["--profile", str(tmp_path)]) == 2
    assert "no profiling data" in capsys.readouterr().err
    assert report.main(["--profile", "--json", str(tmp_path)]) == 2
    doc = json.loads(capsys.readouterr().out)
    assert doc["no_data"] is True


# ---------------------------------------------------------------------------
# Perfetto: the utilization counter track
# ---------------------------------------------------------------------------


def test_perfetto_utilization_counter_track(tmp_path):
    from srnn_tpu.telemetry.fleet import perfetto_trace

    rows = [
        {"kind": "metrics", "t": 1.5, "metrics": {
            "srnn_soup_utilization_device_busy": 0.4,
            "srnn_soup_utilization_host_blocked": 0.3,
            "srnn_soup_utilization_idle": 0.3,
            "srnn_soup_generations_total": 6.0}},
        # a metrics row without utilization gauges emits no track
        {"kind": "metrics", "t": 2.0, "metrics": {
            "srnn_soup_generations_total": 8.0}},
    ]
    with open(tmp_path / "events.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    doc = perfetto_trace(str(tmp_path))
    util = [e for e in doc["traceEvents"] if e["name"] == "utilization"]
    assert len(util) == 1
    ev = util[0]
    assert ev["ph"] == "C" and ev["cat"] == "profile"
    assert ev["ts"] == 1.5e6
    assert ev["args"] == {"device_busy": 0.4, "host_blocked": 0.3,
                          "idle": 0.3}


# ---------------------------------------------------------------------------
# the oracle: the whole plane is host-side
# ---------------------------------------------------------------------------


def _assert_bitwise_equal(a, b):
    import jax

    np.testing.assert_array_equal(np.asarray(a.weights),
                                  np.asarray(b.weights))
    np.testing.assert_array_equal(np.asarray(a.uids), np.asarray(b.uids))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(a.key)),
        np.asarray(jax.random.key_data(b.key)))


def test_no_profile_bitwise_ab_mega_soup(tmp_path):
    """mega_soup with the profiler (default) vs --no-profile:
    weights/uids/PRNG bitwise-identical; the profile artifacts exist
    only in the profiled run."""
    from srnn_tpu.experiment import restore_checkpoint

    with_prof = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "47", "--root", str(tmp_path / "a")])
    without = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "47", "--no-profile",
         "--root", str(tmp_path / "b")])
    _assert_bitwise_equal(
        restore_checkpoint(os.path.join(with_prof, "ckpt-gen00000006")),
        restore_checkpoint(os.path.join(without, "ckpt-gen00000006")))
    assert os.path.exists(os.path.join(with_prof, "profile.folded"))
    assert os.path.exists(os.path.join(with_prof, "profile.jsonl"))
    assert not os.path.exists(os.path.join(without, "profile.folded"))
    assert not os.path.exists(os.path.join(without, "profile.jsonl"))
    # no alert fired in a healthy smoke: no anomaly bundles either way
    assert not os.path.exists(os.path.join(without, "anomaly"))
    prom = open(os.path.join(with_prof, "metrics.prom")).read()
    assert "srnn_soup_profile_samples_total" in prom
    assert "srnn_soup_utilization_device_busy" in prom
    assert "srnn_soup_profile" not in open(
        os.path.join(without, "metrics.prom")).read()


def test_no_profile_bitwise_ab_mega_multisoup(tmp_path):
    from srnn_tpu.experiment import restore_multi_checkpoint

    with_prof = REGISTRY["mega_multisoup"](
        ["--smoke", "--seed", "47", "--root", str(tmp_path / "a")])
    without = REGISTRY["mega_multisoup"](
        ["--smoke", "--seed", "47", "--no-profile",
         "--root", str(tmp_path / "b")])
    a = restore_multi_checkpoint(os.path.join(with_prof,
                                              "ckpt-gen00000006"))
    b = restore_multi_checkpoint(os.path.join(without,
                                              "ckpt-gen00000006"))
    for wa, wb in zip(a.weights, b.weights):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    import jax

    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(a.key)),
        np.asarray(jax.random.key_data(b.key)))
    assert os.path.exists(os.path.join(with_prof, "profile.folded"))
    assert not os.path.exists(os.path.join(without, "profile.folded"))
