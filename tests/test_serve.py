"""Multi-tenant experiment service (srnn_tpu.serve).

The load-bearing contract is STACKED-VS-SOLO BITWISE PARITY: every tenant
slice of a stacked dispatch must carry exactly the bits its solo run
produces — weights, uids, PRNG keys, metrics/health carries, lineage
pids/edges, and captured ``.traj`` streams.  Plus the scheduler's
grouping/fallback semantics, the service end-to-end (one stacked + one
solo dispatch, per-tenant results equal to solo computes), and the
socket transport round trip.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu.multisoup import MultiSoupConfig, evolve_multi, seed_multi
from srnn_tpu.serve import (ExperimentService, plan_dispatches,
                            stack_tenants, unstack_tenants)
from srnn_tpu.serve.scheduler import Request
from srnn_tpu.serve.service import GROUP_KEYS
from srnn_tpu.serve.tenant import (evolve_multi_stacked, evolve_stacked,
                                   evolve_stacked_captured, seed_stacked)
from srnn_tpu.soup import SoupConfig, evolve, seed, tenant_stackable
from srnn_tpu.topology import Topology

WW = Topology("weightwise", width=2, depth=2)
AGG = Topology("aggregating", width=2, depth=2, aggregates=4)

CFG = SoupConfig(topo=WW, size=16, attacking_rate=0.25, learn_from_rate=0.25,
                 train=2, remove_divergent=True, remove_zero=True)
K = 4


def _keyless(x):
    if hasattr(x, "dtype") and jax.dtypes.issubdtype(x.dtype,
                                                     jax.dtypes.prng_key):
        return jax.random.key_data(x)
    return x


def _assert_bits_equal(a, b, what=""):
    """Bitwise equality across a pytree (NaN-safe: compares bit patterns,
    not float values)."""
    la = jax.tree.leaves(jax.tree.map(_keyless, a))
    lb = jax.tree.leaves(jax.tree.map(_keyless, b))
    assert len(la) == len(lb), what
    for i, (x, y) in enumerate(zip(la, lb)):
        x = np.atleast_1d(np.asarray(x))
        y = np.atleast_1d(np.asarray(y))
        assert x.dtype == y.dtype and x.shape == y.shape, \
            f"{what} leaf {i}: {x.dtype}{x.shape} vs {y.dtype}{y.shape}"
        np.testing.assert_array_equal(x.view(np.uint8), y.view(np.uint8),
                                      err_msg=f"{what} leaf {i}")


def _tenant_states(cfg, k=K):
    return [seed(cfg, jax.random.key(t)) for t in range(k)]


# ---------------------------------------------------------------------------
# stacked-vs-solo bitwise parity
# ---------------------------------------------------------------------------


def test_stacked_soup_full_carry_parity():
    """K=4 stacked run with metrics+health+lineage on == 4 solo runs,
    bit for bit: state (incl. PRNG key), carries, lineage pids/edges."""
    from srnn_tpu.telemetry.dynamics import seed_lineage

    states = _tenant_states(CFG)
    lins = [seed_lineage(CFG.size) for _ in range(K)]
    solo = [evolve(CFG, s, generations=5, metrics=True, health=True,
                   lineage=True, lineage_state=l, lineage_capacity=256)
            for s, l in zip(states, lins)]
    out = evolve_stacked(CFG, stack_tenants(states), generations=5,
                         metrics=True, health=True, lineage=True,
                         lineage_state=stack_tenants(lins),
                         lineage_capacity=256)
    for t, got in enumerate(unstack_tenants(out, K)):
        _assert_bits_equal(solo[t], got, what=f"tenant {t}")


def test_stacked_soup_seed_and_events_parity():
    """seed_stacked == per-tenant seed; the recorded per-generation event
    streams (action/counterpart/loss) match too."""
    keys = jnp.stack([jax.random.key(t) for t in range(K)])
    stacked = seed_stacked(CFG, keys)
    for t, got in enumerate(unstack_tenants(stacked, K)):
        _assert_bits_equal(seed(CFG, jax.random.key(t)), got,
                           what=f"seed tenant {t}")
    states = _tenant_states(CFG)
    solo = [evolve(CFG, s, generations=4, record=True) for s in states]
    out = evolve_stacked(CFG, stack_tenants(states), generations=4,
                         record=True)
    for t in range(K):
        _assert_bits_equal(solo[t][1], jax.tree.map(lambda x: x[t], out[1]),
                           what=f"events tenant {t}")


def test_stacked_multisoup_parity():
    mcfg = MultiSoupConfig(topos=(WW, AGG), sizes=(8, 8),
                           attacking_rate=0.25, learn_from_rate=0.25,
                           train=1, remove_divergent=True, remove_zero=True)
    from srnn_tpu.telemetry.dynamics import seed_lineage_blocks

    states = [seed_multi(mcfg, jax.random.key(t)) for t in range(K)]
    lins = [seed_lineage_blocks(mcfg.sizes) for _ in range(K)]
    solo = [evolve_multi(mcfg, s, generations=4, metrics=True, health=True,
                         lineage=True, lineage_state=l,
                         lineage_capacity=256)
            for s, l in zip(states, lins)]
    out = evolve_multi_stacked(mcfg, stack_tenants(states), generations=4,
                               metrics=True, health=True, lineage=True,
                               lineage_state=stack_tenants(lins),
                               lineage_capacity=256)
    for t in range(K):
        _assert_bits_equal(solo[t], jax.tree.map(lambda x: x[t], out),
                           what=f"multi tenant {t}")


def test_stacked_traj_capture_parity(tmp_path):
    """Per-tenant ``.traj`` streams from one stacked captured run equal
    the solo ``evolve_captured`` streams (same stride, same donated
    dispatch order), frame for frame."""
    from srnn_tpu.utils import TrajStore, evolve_captured
    from srnn_tpu.utils.trajstore import read_store

    gens, every = 6, 2
    states = _tenant_states(CFG)
    for t, st in enumerate(states):
        with TrajStore(str(tmp_path / f"solo{t}.traj"), CFG.size,
                       CFG.topo.num_weights) as store:
            evolve_captured(CFG, st, gens, store, every=every)
    stores = [TrajStore(str(tmp_path / f"stk{t}.traj"), CFG.size,
                        CFG.topo.num_weights) for t in range(K)]
    try:
        evolve_stacked_captured(CFG, stack_tenants(states), gens, stores,
                                every=every)
    finally:
        for s in stores:
            s.close()
    for t in range(K):
        ref = read_store(str(tmp_path / f"solo{t}.traj"))
        got = read_store(str(tmp_path / f"stk{t}.traj"))
        _assert_bits_equal(ref, got, what=f"traj tenant {t}")


def test_stackability_gate():
    assert tenant_stackable(CFG)
    pm = CFG._replace(layout="popmajor", respawn_draws="fused")
    assert not tenant_stackable(pm)
    with pytest.raises(ValueError, match="rowmajor"):
        evolve_stacked(pm, stack_tenants(_tenant_states(CFG)),
                       generations=1)
    assert not tenant_stackable(CFG._replace(mode="sequential"))


def test_engine_stacked_parity():
    from srnn_tpu.engine import (fixpoint_density, fixpoint_density_stacked,
                                 run_fixpoint, run_fixpoint_stacked)
    from srnn_tpu.init import init_population

    pops = [init_population(WW, jax.random.key(t), 32) for t in range(K)]
    eps = jnp.asarray([1e-4, 1e-3, 1e-4, 1e-5], jnp.float32)
    stacked = fixpoint_density_stacked(WW, jnp.stack(pops), eps)
    for t in range(K):
        np.testing.assert_array_equal(
            np.asarray(fixpoint_density(WW, pops[t], float(eps[t]))),
            np.asarray(stacked[t]))
    st = run_fixpoint_stacked(WW, jnp.stack(pops), step_limit=8,
                              epsilons=eps)
    for t in range(K):
        solo = run_fixpoint(WW, pops[t], step_limit=8,
                            epsilon=float(eps[t]))
        _assert_bits_equal([solo.weights, solo.steps, solo.classes,
                            solo.counts],
                           [st.weights[t], st.steps[t], st.classes[t],
                            st.counts[t]], what=f"fixpoint tenant {t}")


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------


def _req(i, kind, **params):
    return Request(ticket=f"t{i}", kind=kind, params=params,
                   tenant=f"t{i}", submitted_s=0.0)


def test_scheduler_groups_and_falls_back():
    reqs = [
        _req(0, "fixpoint_density", trials=64, batch=32, seed=0),
        _req(1, "fixpoint_density", trials=64, batch=32, seed=1),
        _req(2, "fixpoint_density", trials=48, batch=24, seed=2),  # odd
        _req(3, "soup", size=8, generations=4, seed=0),
        _req(4, "soup", size=8, generations=4, seed=1),
        _req(5, "soup", size=8, generations=4, seed=2,
             layout="popmajor"),  # unstackable config -> solo
    ]
    # the popmajor request's key function must return None (solo)
    assert GROUP_KEYS["soup"](reqs[5].params) is None
    plan = plan_dispatches(reqs, GROUP_KEYS, max_stack=8)
    modes = [(d.kind, len(d.requests)) for d in plan]
    assert ("fixpoint_density", 2) in modes
    assert ("fixpoint_density", 1) in modes
    assert ("soup", 2) in modes
    assert ("soup", 1) in modes
    # chunking: 5 same-key requests at max_stack=2 -> 2+2+1
    many = [_req(i, "fixpoint_density", trials=64, batch=32, seed=i)
            for i in range(5)]
    sizes = [len(d.requests) for d in
             plan_dispatches(many, GROUP_KEYS, max_stack=2)]
    assert sizes == [2, 2, 1]


# ---------------------------------------------------------------------------
# service end-to-end (in-process)
# ---------------------------------------------------------------------------


def test_service_stacks_matching_and_solos_odd(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"), max_stack=8)
    with svc:
        t1 = svc.submit("fixpoint_density",
                        {"seed": 0, "trials": 64, "batch": 32}, tenant="a")
        t2 = svc.submit("fixpoint_density",
                        {"seed": 1, "trials": 64, "batch": 32}, tenant="b")
        t3 = svc.submit("fixpoint_density",
                        {"seed": 2, "trials": 48, "batch": 24}, tenant="c")
        assert svc.run_pending() == 3
        e1, e2, e3 = (svc.poll(t) for t in (t1, t2, t3))
        assert (e1["mode"], e2["mode"], e3["mode"]) == \
            ("stacked", "stacked", "solo")
        # per-tenant results == the solo compute of the same sweep
        from srnn_tpu.engine import fixpoint_density
        from srnn_tpu.init import init_population
        from srnn_tpu.setups.common import STANDARD_VARIANTS

        for entry, seed_, trials, batch in ((e1, 0, 64, 32),
                                            (e2, 1, 64, 32),
                                            (e3, 2, 48, 24)):
            key = jax.random.key(seed_)
            for v, (_name, topo) in enumerate(STANDARD_VARIANTS[:2]):
                total = jnp.zeros(5, jnp.int32)
                done = 0
                while done < trials:
                    n = min(batch, trials - done)
                    pop = init_population(
                        topo,
                        jax.random.fold_in(jax.random.fold_in(key, v),
                                           done), n)
                    total = total + fixpoint_density(topo, pop, 1e-4)
                    done += n
                assert entry["result"]["counters"][v] == \
                    np.asarray(total).tolist()
        reg = svc.registry
        assert reg.counter("serve_dispatches_total").value(
            kind="fixpoint_density", mode="stacked") == 1
        assert reg.counter("serve_dispatches_total").value(
            kind="fixpoint_density", mode="solo") == 1
        svc.writer.flush()
    prom = (tmp_path / "svc" / "metrics.prom").read_text()
    assert 'srnn_serve_dispatches_total{kind="fixpoint_density",' \
           'mode="stacked"} 1' in prom


def test_service_soup_matches_solo_and_streams_lineage(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"), max_stack=8)
    with svc:
        params = {"size": 12, "generations": 4, "train": 1,
                  "attacking_rate": 0.25, "remove_divergent": True,
                  "remove_zero": True, "lineage": True}
        tickets = [svc.submit("soup", dict(params, seed=i),
                              tenant=f"tenant{i}") for i in range(3)]
        svc.run_pending()
        entries = [svc.poll(t) for t in tickets]
        assert all(e["mode"] == "stacked" for e in entries)
        # oracle: the solo run of tenant 1
        from srnn_tpu.serve.service import _soup_config_from_params
        from srnn_tpu.soup import count

        cfg = _soup_config_from_params(params)
        final = evolve(cfg, seed(cfg, jax.random.key(1)), generations=4)
        assert entries[1]["result"]["counters"] == \
            np.asarray(count(cfg, final)).tolist()
        np.testing.assert_array_equal(
            np.asarray(entries[1]["result"]["weights"], np.float32),
            np.asarray(final.weights))
        svc.writer.flush()
        rows = [json.loads(l) for l in
                open(os.path.join(svc.root, "lineage.jsonl"))]
        assert [r["tenant"] for r in rows] == ["tenant0", "tenant1",
                                               "tenant2"]
        assert all(r["kind"] == "window" for r in rows)
    # events.jsonl carries tenant-labeled rows through the writer
    events = [json.loads(l) for l in
              open(os.path.join(str(tmp_path / "svc"), "events.jsonl"))]
    tenant_rows = [e for e in events if e.get("kind") == "serve_tenant"]
    assert {e["tenant"] for e in tenant_rows} == {"tenant0", "tenant1",
                                                  "tenant2"}


def test_soup_request_schema_defaults_match_soupconfig():
    """Unstated request knobs must take SoupConfig's OWN defaults — a
    drifted default here once ran service tenants at lr=0.1 against solo
    processes at DEFAULT_LR=0.01 (caught as a weights mismatch)."""
    from srnn_tpu.serve.service import _soup_config_from_params

    assert _soup_config_from_params({"size": 8}) == \
        SoupConfig(topo=WW, size=8)


def test_service_failed_request_reports_error(tmp_path):
    svc = ExperimentService(str(tmp_path / "svc"))
    with svc:
        with pytest.raises(ValueError):
            svc.submit("no_such_kind", {})
        # a soup request with an invalid config fails its dispatch but
        # leaves the service serving
        t1 = svc.submit("soup", {"size": 8, "generations": 2,
                                 "train_mode": "bogus"})
        # malformed params whose GROUP-KEY computation raises (no "size")
        # must fail only their own request, not the scheduling round
        t0 = svc.submit("soup", {"generations": 2})
        t2 = svc.submit("fixpoint_density",
                        {"seed": 0, "trials": 32, "batch": 32})
        svc.run_pending()
        assert svc.poll(t1)["status"] == "failed"
        assert "bogus" in svc.poll(t1)["error"]
        assert svc.poll(t0)["status"] == "failed"
        assert svc.poll(t2)["status"] == "done"
        assert svc.registry.counter("serve_requests_failed_total").value(
            kind="soup") == 2
        # wait() CONSUMES its entry (bounded results table under load)
        assert svc.wait(t2, timeout_s=5)["status"] == "done"
        assert svc.poll(t2) is None


# ---------------------------------------------------------------------------
# socket transport
# ---------------------------------------------------------------------------


def test_socket_server_round_trip(tmp_path):
    from srnn_tpu.serve.client import ServiceClient, ServiceError
    from srnn_tpu.serve.server import ServiceServer
    from srnn_tpu.utils.pipeline import spawn_thread

    svc = ExperimentService(str(tmp_path / "svc"), max_stack=4)
    sock = str(tmp_path / "serve.sock")
    server = ServiceServer(svc, sock, batch_window_s=0.05)
    thread = spawn_thread(server.serve_until_shutdown, name="test-serve")
    try:
        client = ServiceClient(sock)
        client.wait_until_up(30)
        result = client.request(
            "fixpoint_density", {"seed": 3, "trials": 32, "batch": 32},
            tenant="sock", timeout_s=120)
        assert len(result["counters"]) == 2
        assert client.stats()["completed"] == 1
        with pytest.raises(ServiceError, match="unknown"):
            client._op({"op": "nope"})
    finally:
        ServiceClient(sock).shutdown()
        thread.join(timeout=30)
        svc.close()
    assert not thread.is_alive()
    assert not os.path.exists(sock)


@pytest.mark.slow
def test_service_process_end_to_end(tmp_path):
    """Real service PROCESS on a Unix socket; two same-shape setups
    clients stack, an odd one solos, artifacts bitwise-match local runs,
    metrics.prom records the dispatch modes, clean --shutdown."""
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["SRNN_SETUPS_PLATFORM"] = "cpu"
    env.pop("PYTHONPATH", None)
    root = str(tmp_path / "svc")
    sock = os.path.join(root, "serve.sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "srnn_tpu.serve", "--root", root,
         "--batch-window-s", "2"], cwd=repo, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if subprocess.run(
                    [sys.executable, "-m", "srnn_tpu.serve", "--socket",
                     sock, "--ping"], cwd=repo, env=env).returncode == 0:
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("service never answered ping")

        def client(seed, extra):
            return subprocess.Popen(
                [sys.executable, "-m", "srnn_tpu.setups",
                 "fixpoint_density", "--seed", str(seed), "--root",
                 str(tmp_path / "exp"), "--service", sock] + extra,
                cwd=repo, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT)

        clients = [client(0, ["--smoke"]), client(1, ["--smoke"]),
                   client(2, ["--trials", "48", "--batch", "24"])]
        for c in clients:
            assert c.wait(timeout=240) == 0
        assert subprocess.run(
            [sys.executable, "-m", "srnn_tpu.serve", "--socket", sock,
             "--shutdown"], cwd=repo, env=env).returncode == 0
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
    prom = open(os.path.join(root, "metrics.prom")).read()
    assert 'mode="stacked"} 1' in prom and 'mode="solo"} 1' in prom
    # tenant 1's artifacts == a local (process-mode) run of the same sweep
    local = subprocess.run(
        [sys.executable, "-m", "srnn_tpu.setups", "fixpoint_density",
         "--seed", "1", "--smoke", "--root", str(tmp_path / "local")],
        cwd=repo, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, timeout=240)
    local_dir = local.stdout.decode().strip().splitlines()[-1]
    import glob

    svc_runs = glob.glob(str(tmp_path / "exp" / "exp-*"))
    match = [d for d in svc_runs
             if json.load(open(os.path.join(d, "meta.json")))["seed"] == 1]
    a = np.load(os.path.join(match[0], "all_counters.npz"))
    b = np.load(os.path.join(local_dir, "all_counters.npz"))
    np.testing.assert_array_equal(a[a.files[0]], b[b.files[0]])
    assert json.load(open(os.path.join(
        match[0], "config.json")))["execution_mode"] == "service"


# ---------------------------------------------------------------------------
# AOT warmup spellings
# ---------------------------------------------------------------------------


def test_stacked_warmup_entry_names():
    """The stacked spelling zoo exists for stackable configs (names only —
    compiles are covered by warmup tests in test_aot) and is empty for
    popmajor ones."""
    from srnn_tpu.utils import aot

    names = [j[0] for j in aot._stacked_entries(CFG, 4, 2, donate=True)]
    assert "serve.evolve_stacked.donated.metered" in names
    assert "serve.evolve_stacked.donated.metered.lineage" in names
    pm = CFG._replace(layout="popmajor", respawn_draws="fused")
    assert list(aot._stacked_entries(pm, 4, 2, donate=True)) == []
    mcfg = MultiSoupConfig(topos=(WW, AGG), sizes=(8, 8))
    mnames = [j[0] for j in aot._stacked_multi_entries(mcfg, 4, 2, False)]
    assert "serve.evolve_multi_stacked.metered.lineage" in mnames
