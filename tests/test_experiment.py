"""Experiment runtime: run dirs, logging, artifacts, checkpoint/resume."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology
from srnn_tpu.experiment import (
    Experiment,
    counters_dict,
    format_counters,
    load_artifact,
    restore_checkpoint,
    save_artifact,
    save_checkpoint,
)
from srnn_tpu.soup import SoupConfig, evolve, seed


def test_run_dir_and_log(tmp_path):
    exp = Experiment("demo", ident="t", root=str(tmp_path))
    with exp as e:
        e.log("hello")
        e.log("counters: {'divergent': 1}")
        run_dir = e.dir
    assert os.path.isdir(run_dir)
    assert run_dir.endswith("-0")
    lines = open(os.path.join(run_dir, "log.txt")).read().splitlines()
    assert lines == ["hello", "counters: {'divergent': 1}"]
    meta = json.load(open(os.path.join(run_dir, "meta.json")))
    assert meta["name"] == "demo" and meta["error"] is None
    # second entry gets the next iteration suffix (experiment.py:33)
    with exp as e:
        second = e.dir
    assert second.endswith("-1") and second != run_dir


def test_structured_events(tmp_path):
    with Experiment("ev", root=str(tmp_path)) as e:
        e.log("step done", step=3, counts=np.array([1, 2]))
        e.event(kind="checkpoint", gen=7)
        run_dir = e.dir
    recs = [json.loads(l) for l in open(os.path.join(run_dir, "events.jsonl"))]
    assert recs[0]["step"] == 3 and recs[0]["counts"] == [1, 2]
    assert recs[1]["kind"] == "checkpoint" and "t" in recs[1]


def test_artifact_roundtrip_array_and_pytree(tmp_path):
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    f = save_artifact(str(tmp_path / "a"), arr)
    assert f.endswith(".npz")
    back = load_artifact(str(tmp_path / "a"))
    np.testing.assert_array_equal(back, arr)

    tree = {"xs": np.arange(3), "nested": {"ys": jnp.ones(2)}}
    save_artifact(str(tmp_path / "tree"), tree)
    back = load_artifact(str(tmp_path / "tree"))
    np.testing.assert_array_equal(back["xs"], np.arange(3))
    np.testing.assert_array_equal(back["nested/ys"], np.ones(2))


def test_artifact_json_fallback(tmp_path):
    value = {"names": ["ww", "agg"], "rate": 0.1}
    f = save_artifact(str(tmp_path / "names"), value)
    assert f.endswith(".json")
    assert load_artifact(str(tmp_path / "names")) == value


def test_artifact_prng_key_and_collisions(tmp_path):
    # typed PRNG keys are stored as raw key data, not a crash
    state_like = {"w": jnp.ones(3), "key": jax.random.key(0)}
    save_artifact(str(tmp_path / "st"), state_like)
    back = load_artifact(str(tmp_path / "st"))
    np.testing.assert_array_equal(
        back["key"], np.asarray(jax.random.key_data(jax.random.key(0))))
    # separator collisions are an error, not silent data loss
    with pytest.raises(ValueError, match="collision"):
        save_artifact(str(tmp_path / "c"), {"a": {"b": np.zeros(1)}, "a/b": np.ones(1)})
    # a dict whose only key is 'value' survives as a dict
    save_artifact(str(tmp_path / "v"), {"value": np.arange(3)})
    assert set(load_artifact(str(tmp_path / "v"))) == {"value"}


def test_experiment_save_load(tmp_path):
    with Experiment("s", root=str(tmp_path)) as e:
        e.save(all_counters=jnp.array([1, 2, 3, 4, 5]), all_names={"n": ["x"]})
        np.testing.assert_array_equal(e.load("all_counters"), [1, 2, 3, 4, 5])


def test_format_counters_matches_reference_repr():
    counts = jnp.array([23, 27, 0, 0, 0])
    assert format_counters(counts) == (
        "{'divergent': 23, 'fix_zero': 27, 'fix_other': 0, 'fix_sec': 0, 'other': 0}")
    assert counters_dict(counts)["divergent"] == 23


def test_checkpoint_resume_bit_exact(tmp_path):
    """A soup restored from a checkpoint must continue exactly as the
    original would have (weights, uids AND PRNG stream)."""
    cfg = SoupConfig(topo=Topology("weightwise"), size=8,
                     attacking_rate=0.3, learn_from_rate=0.0, train=0,
                     remove_divergent=True, remove_zero=True)
    state = seed(cfg, jax.random.key(7))
    mid = evolve(cfg, state, generations=3)

    path = save_checkpoint(str(tmp_path / "ckpt"), mid)
    restored = restore_checkpoint(path)
    np.testing.assert_array_equal(np.asarray(restored.weights), np.asarray(mid.weights))
    assert int(restored.time) == 3

    cont_a = evolve(cfg, mid, generations=2)
    cont_b = evolve(cfg, restored, generations=2)
    np.testing.assert_array_equal(np.asarray(cont_a.weights), np.asarray(cont_b.weights))
    np.testing.assert_array_equal(np.asarray(cont_a.uids), np.asarray(cont_b.uids))
    assert int(cont_b.time) == 5
