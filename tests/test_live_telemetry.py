"""Live telemetry plane (PR 15): the OpenMetrics HTTP exporter under
concurrent scrapes + registry mutation, metric-history ring overflow
semantics, the declarative alert engine's fire/clear/absence edges, the
serve queue-depth alert through the journal-replay path, and the
``--no-export`` bitwise A/B oracle for both mega loops."""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from srnn_tpu.setups import REGISTRY
from srnn_tpu.telemetry.alerts import (AlertEngine, Rule,
                                       default_run_rules,
                                       default_serve_rules)
from srnn_tpu.telemetry.exporter import (HEALTHZ_METRICS, MetricsExporter,
                                         healthz_metrics, worker_liveness)
from srnn_tpu.telemetry.metrics import MetricsRegistry
from srnn_tpu.telemetry.timeseries import (MetricHistory,
                                           load_history_rows, sparkline,
                                           summarize_history)


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), \
            r.read().decode("utf-8")


# ---------------------------------------------------------------------------
# exporter: /metrics + /healthz, concurrency, failure modes
# ---------------------------------------------------------------------------


def test_exporter_serves_metrics_and_healthz():
    reg = MetricsRegistry()
    reg.gauge("serve_queue_depth", help="q").set(3)
    reg.counter("soup_generations_total", help="g").inc(7)
    with MetricsExporter(reg, port=0,
                         healthz=lambda: {"ok": True, "stage": "t"}) as ex:
        status, ctype, body = _get(ex.url + "/metrics")
        assert status == 200 and "version=0.0.4" in ctype
        assert "srnn_serve_queue_depth 3" in body
        assert "srnn_soup_generations_total 7" in body
        # the response never includes its own scrape, but the NEXT one
        # counts it — the exporter observes itself
        _status, _ctype, body2 = _get(ex.url + "/metrics")
        assert 'srnn_soup_scrapes_total{endpoint="metrics"} 1' in body2

        status, ctype, body = _get(ex.url + "/healthz")
        assert status == 200 and ctype.startswith("application/json")
        doc = json.loads(body)
        assert doc["ok"] is True and doc["stage"] == "t"
        assert "uptime_s" in doc and doc["port"] == ex.port

        with pytest.raises(urllib.error.HTTPError) as e:
            _get(ex.url + "/nope")
        assert e.value.code == 404
    # closed: the port no longer answers
    with pytest.raises(OSError):
        urllib.request.urlopen(ex.url + "/metrics", timeout=1)


def test_exporter_unhealthy_healthz_is_503():
    reg = MetricsRegistry()
    with MetricsExporter(reg, port=0,
                         healthz=lambda: {"ok": False,
                                          "reason": "worker stale"}) as ex:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(ex.url + "/healthz")
        assert e.value.code == 503
        doc = json.loads(e.value.read().decode())
        assert doc["ok"] is False and doc["reason"] == "worker stale"
        # a RAISING provider is itself a 503, never a hung handler
        ex._healthz = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(ex.url + "/healthz")
        assert e.value.code == 503


def test_exporter_concurrent_scrapes_under_registry_mutation():
    """Thread-safety: scrapes racing live registry mutation (new metrics
    registering mid-scrape included) always parse — every non-comment
    line is `name value` — and every scrape is counted."""
    reg = MetricsRegistry()
    c = reg.counter("soup_generations_total", help="g")
    stop = threading.Event()

    def mutate():
        i = 0
        while not stop.is_set():
            c.inc(1)
            reg.gauge("soup_class_particles", help="p").set(i, cls=str(i % 5))
            reg.histogram("span_seconds", help="s").observe(0.01 * i,
                                                            span=str(i % 3))
            i += 1

    scrapes_per_thread = 25
    bodies = []
    errors = []

    def scrape(url):
        try:
            for _ in range(scrapes_per_thread):
                bodies.append(_get(url)[2])
        except Exception as e:  # pragma: no cover - the assertion payload
            errors.append(e)

    with MetricsExporter(reg, port=0) as ex:
        mut = threading.Thread(target=mutate)
        mut.start()
        threads = [threading.Thread(target=scrape,
                                    args=(ex.url + "/metrics",))
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        mut.join()
        assert not errors, errors
        assert len(bodies) == 4 * scrapes_per_thread
        for body in bodies:
            for line in body.splitlines():
                if line and not line.startswith("#"):
                    name, _sep, value = line.rpartition(" ")
                    assert name
                    float(value)  # parses
        assert ex.scrapes == 4 * scrapes_per_thread


def test_healthz_metrics_allowlist_slice():
    reg = MetricsRegistry()
    reg.gauge("heartbeat_generation", help="g").set(12, stage="s")
    reg.gauge("soup_class_particles", help="p").set(5)  # not allowlisted
    out = healthz_metrics(reg)
    assert out == {'srnn_heartbeat_generation{stage="s"}': 12}
    # every allowlisted name is a declared canonical metric (the M006
    # gate's runtime twin)
    from srnn_tpu.telemetry.names import CANONICAL_METRICS
    assert set(HEALTHZ_METRICS) <= set(CANONICAL_METRICS)


def test_worker_liveness_from_heartbeat_lanes(tmp_path):
    run_dir = str(tmp_path)
    open(os.path.join(run_dir, "events.jsonl"), "w").write("{}\n")
    open(os.path.join(run_dir, "events-p1.jsonl"), "w").write("{}\n")
    live = worker_liveness(run_dir, 3, stale_after_s=60.0)
    assert live["0"]["ok"] and live["1"]["ok"]
    assert live["2"] == {"age_s": None, "ok": False}  # missing lane
    stale = worker_liveness(run_dir, 2, stale_after_s=-1.0)
    assert not stale["0"]["ok"]  # age > bound -> stale


# ---------------------------------------------------------------------------
# history rings
# ---------------------------------------------------------------------------


def test_history_ring_overflow_and_jsonl_stream(tmp_path):
    path = str(tmp_path / "metrics_history.jsonl")
    reg = MetricsRegistry()
    c = reg.counter("soup_generations_total", help="g")
    h = MetricHistory(reg, capacity=4, path=path)
    for i in range(10):
        c.inc(5)
        h.sample(t=float(i))
    # overflow: newest `capacity` points kept, evictions counted
    pts = h.series("soup_generations_total")
    assert [t for t, _v in pts] == [6.0, 7.0, 8.0, 9.0]
    assert h.dropped_points == 6 and h.samples_total == 10
    assert h.latest_sum("soup_generations_total") == 50.0
    assert h.age_s("soup_generations_total", now=11.0) == 2.0
    assert h.latest_sum("never_registered") is None
    # rate over the in-ring window: +5 per 1s step
    assert h.rate("soup_generations_total", window_s=10.0,
                  now=9.0) == pytest.approx(5.0)
    # a single in-window point is no evidence: None, not 0.0
    assert h.rate("soup_generations_total", window_s=0.5, now=9.2) is None
    h.close()
    # the jsonl stream keeps the FULL trail (rings bound memory, not
    # disk) and the reader skips torn lines
    with open(path, "a") as f:
        f.write('{"kind": "metrics_history", "t":\n')
    rows = load_history_rows(path)
    assert len(rows) == 10
    assert rows[-1]["metrics"]["srnn_soup_generations_total"] == 50
    digest = summarize_history(path)
    assert digest["samples"] == 10
    ser = digest["series"]["soup_generations_total"]
    assert ser["first"] == 5 and ser["last"] == 50
    assert ser["rate_per_s"] == pytest.approx(5.0)
    assert len(ser["spark"]) == 10


def test_history_label_sets_fold_by_sum():
    reg = MetricsRegistry()
    g = reg.gauge("soup_straggler_gens_per_second", help="r")
    g.set(10.0, process="0")
    g.set(4.0, process="1")
    h = MetricHistory(reg, capacity=8)
    h.sample(t=0.0)
    assert h.latest_sum("soup_straggler_gens_per_second") == 14.0


def test_sparkline_shapes():
    assert sparkline([]) == ""
    assert sparkline([3, 3, 3]) == "▁▁▁"
    s = sparkline(range(100), width=16)
    assert len(s) == 16 and s[0] == "▁" and s[-1] == "█"


# ---------------------------------------------------------------------------
# alert engine: fire / clear / absence
# ---------------------------------------------------------------------------


def test_alert_threshold_fires_clears_and_counts():
    reg = MetricsRegistry()
    nan = reg.gauge("soup_health_nan_frac", help="f")
    h = MetricHistory(reg, capacity=16)
    eng = AlertEngine(default_run_rules(nan_frac=0.5), reg, h)
    nan.set(0.1)
    h.sample(t=0.0)
    assert eng.evaluate(now=0.0) == []
    nan.set(0.9)
    h.sample(t=1.0)
    trs = eng.evaluate(now=1.0)
    assert [(t["rule"], t["state"]) for t in trs] == \
        [("soup_nan_frac", "firing")]
    assert trs[0]["value"] == pytest.approx(0.9)
    assert trs[0]["threshold"] == 0.5
    assert reg.counter("soup_alerts_total").value(rule="soup_nan_frac") == 1
    assert reg.gauge("soup_alerts_active").value() == 1
    active = eng.active()
    assert len(active) == 1 and active[0]["rule"] == "soup_nan_frac"
    # latched: still firing -> NO new transition, counter unchanged
    h.sample(t=2.0)
    assert eng.evaluate(now=2.0) == []
    assert reg.counter("soup_alerts_total").value(rule="soup_nan_frac") == 1
    # recovery: one cleared edge, active set empties
    nan.set(0.0)
    h.sample(t=3.0)
    trs = eng.evaluate(now=3.0)
    assert [(t["rule"], t["state"]) for t in trs] == \
        [("soup_nan_frac", "cleared")]
    assert eng.active() == []
    assert reg.gauge("soup_alerts_active").value() == 0


def test_alert_rate_and_absence_rules():
    reg = MetricsRegistry()
    viol = reg.counter("serve_slo_violations_total", help="v")
    viol.inc(0, kind="soup")   # materialize the series at 0 (the serve
    #                            layer registers its counters eagerly)
    h = MetricHistory(reg, capacity=64)
    eng = AlertEngine(
        [Rule(name="burn", metric="serve_slo_violations_total",
              kind="rate", op=">", value=0.0, window_s=10.0),
         Rule(name="hb_gone", metric="heartbeat_generation",
              kind="absence", window_s=5.0)], reg, h)
    h.sample(t=0.0)
    assert eng.evaluate(now=0.0) == []     # grace: absence needs a window
    h.sample(t=2.0)
    assert eng.evaluate(now=2.0) == []     # flat counter: no burn
    # a never-sampled metric past the grace window IS an absence
    trs = eng.evaluate(now=6.0)
    assert [(t["rule"], t["state"]) for t in trs] == [("hb_gone", "firing")]
    # the metric appearing clears the absence
    reg.gauge("heartbeat_generation", help="g").set(4, stage="s")
    h.sample(t=7.0)
    trs = eng.evaluate(now=7.0)
    assert [(t["rule"], t["state"]) for t in trs] == [("hb_gone", "cleared")]
    # counter movement inside the window fires the rate rule...
    viol.inc(3, kind="soup")
    h.sample(t=8.0)
    trs = eng.evaluate(now=8.0)
    assert [(t["rule"], t["state"]) for t in trs] == [("burn", "firing")]
    # ...and a quiet window clears it (old points age out).  The
    # heartbeat gauge stays present in the registry, so continued
    # sampling keeps refreshing its series — no absence re-fire while
    # the sampler itself is alive (absence watches for the metric never
    # appearing, or the whole sampling cadence stopping).
    h.sample(t=20.0)
    h.sample(t=22.0)
    trs = eng.evaluate(now=22.0)
    assert [(t["rule"], t["state"]) for t in trs] == [("burn", "cleared")]


def test_rule_validation_and_bad_specs():
    with pytest.raises(ValueError):
        Rule(name="r", metric="m", kind="nope")
    with pytest.raises(ValueError):
        Rule(name="r", metric="m", op="!=")


def test_default_rule_tables_reference_declared_metrics():
    """Runtime twin of srnnlint M006: every metric the shipped rule
    tables watch is a declared canonical name."""
    from srnn_tpu.telemetry.names import CANONICAL_METRICS
    for rule in default_run_rules() + default_serve_rules(max_queue=8):
        assert rule.metric in CANONICAL_METRICS, rule


# ---------------------------------------------------------------------------
# serve: the queue-depth alert through the journal-replay (serve_kill
# recovery) path — run_tests.sh's serve_chaos_smoke drills the same rule
# through a REAL SIGKILLed service process
# ---------------------------------------------------------------------------


def test_serve_replay_burst_fires_queue_depth_alert(tmp_path):
    """A restarted service replaying journaled tickets restores a
    queue at the admission bound before any dispatch: the
    serve_queue_full rule must fire (events row + stats), then clear
    once the drain empties the queue."""
    from srnn_tpu.serve.service import ExperimentService

    root = str(tmp_path)
    svc = ExperimentService(root)
    for i in range(6):
        svc.submit("fixpoint_density", {"seed": i, "trials": 8, "batch": 8},
                   tenant=f"t{i}")
    svc.close()   # admitted-but-undispatched: journaled unfinished

    svc2 = ExperimentService(root, max_queue=6)
    hist = MetricHistory(svc2.registry,
                         path=os.path.join(root, "metrics_history.jsonl"))
    eng = AlertEngine(default_serve_rules(max_queue=6), svc2.registry, hist)
    svc2.attach_live(hist, eng)
    assert svc2.recover() == 6
    assert svc2.run_pending() == 6
    stats = svc2.stats()
    assert stats["alerts"]["fired"] >= 1
    assert stats["alerts"]["active"] == []   # drained -> cleared
    svc2.close()
    rows = [json.loads(line) for line
            in open(os.path.join(root, "events.jsonl"))
            if '"kind": "alert"' in line]
    states = [(r["rule"], r["state"]) for r in rows]
    assert ("serve_queue_full", "firing") in states
    assert ("serve_queue_full", "cleared") in states
    # the history stream landed in the service root alongside events
    assert load_history_rows(os.path.join(root, "metrics_history.jsonl"))


def test_serve_idle_sampling_clears_rate_alert(tmp_path):
    """A fired rate alert must clear while the service sits IDLE: the
    dispatcher's idle ticks call the throttled ``idle_sample_live``, so
    the window slides past the old violations without new traffic
    (before the fix, sampling only ran inside ``run_pending`` and the
    alert latched firing until the next request)."""
    from srnn_tpu.serve.service import ExperimentService

    svc = ExperimentService(str(tmp_path))
    hist = MetricHistory(svc.registry)
    eng = AlertEngine([Rule(name="burn",
                            metric="serve_slo_violations_total",
                            kind="rate", op=">", value=0.0,
                            window_s=0.2)], svc.registry, hist)
    svc.attach_live(hist, eng)
    svc.registry.counter("serve_slo_violations_total", help="v").inc(
        0, kind="soup")
    svc._sample_live()
    svc.registry.counter("serve_slo_violations_total").inc(3, kind="soup")
    svc._sample_live()
    assert [a["rule"] for a in eng.active()] == ["burn"]
    # throttle: an immediate idle tick is a no-op (no history growth)
    n = hist.samples_total
    svc.idle_sample_live(min_interval_s=60.0)
    assert hist.samples_total == n
    # past the throttle AND the rate window: the idle tick clears it
    import time as _t

    _t.sleep(0.25)
    svc.idle_sample_live(min_interval_s=0.0)
    assert eng.active() == []
    svc.close()


def test_watch_alert_panel_survives_tail_overflow(tmp_path):
    """Rules latch — ONE firing row per long-lived alert — so the watch
    panel scans the whole events file, not a tail: a firing edge buried
    under >256KB of later heartbeat rows must still render as active."""
    from srnn_tpu.telemetry.watch import snapshot

    run_dir = str(tmp_path)
    with open(os.path.join(run_dir, "events.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "alert", "rule": "soup_nan_frac",
                            "state": "firing", "t": 1.0}) + "\n")
        pad = {"kind": "heartbeat", "generation": 0, "t": 2.0,
               "pad": "x" * 256}
        for i in range(1500):   # ~400KB of later rows
            pad["generation"] = i
            f.write(json.dumps(pad) + "\n")
    s = snapshot(run_dir)
    assert s["alerts"] == {"fired": 1, "active": ["soup_nan_frac"]}


def test_exporter_bind_conflict_raises_oserror():
    """The CLI wiring (make_live_plane, serve __main__) catches OSError
    and continues without the endpoint — observability must never take
    down a run.  Pin the exception type that contract relies on."""
    import socket

    reg = MetricsRegistry()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    s.listen(1)
    port = s.getsockname()[1]
    try:
        with pytest.raises(OSError):
            MetricsExporter(reg, port=port)
    finally:
        s.close()


def test_default_run_rules_have_no_absence_kind():
    """The run table deliberately carries no own-heartbeat absence rule:
    every registered series is re-stamped each sample and a wedged loop
    stops evaluation with the cadence, so an in-process absence rule is
    structurally unable to fire — false coverage, worse than none."""
    assert [r.kind for r in default_run_rules()
            if r.kind == "absence"] == []


# ---------------------------------------------------------------------------
# the oracle: the whole plane is host-side
# ---------------------------------------------------------------------------


def _assert_bitwise_equal(a, b):
    import jax

    np.testing.assert_array_equal(np.asarray(a.weights),
                                  np.asarray(b.weights))
    np.testing.assert_array_equal(np.asarray(a.uids), np.asarray(b.uids))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(a.key)),
        np.asarray(jax.random.key_data(b.key)))


def test_no_export_bitwise_ab_mega_soup(tmp_path):
    """mega_soup with the live plane (default) vs --no-export:
    weights/uids/PRNG bitwise-identical; the history stream and alert
    machinery exist only in the default run."""
    from srnn_tpu.experiment import restore_checkpoint

    with_plane = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "47", "--root", str(tmp_path / "a")])
    without = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "47", "--no-export",
         "--root", str(tmp_path / "b")])
    _assert_bitwise_equal(
        restore_checkpoint(os.path.join(with_plane, "ckpt-gen00000006")),
        restore_checkpoint(os.path.join(without, "ckpt-gen00000006")))
    assert os.path.exists(os.path.join(with_plane,
                                       "metrics_history.jsonl"))
    assert not os.path.exists(os.path.join(without,
                                           "metrics_history.jsonl"))
    # one history sample per chunk rode the writer
    rows = load_history_rows(os.path.join(with_plane,
                                          "metrics_history.jsonl"))
    assert len(rows) == 3   # 6 generations / checkpoint-every 2
    # the alert plane registered its series in the flushed registry
    prom = open(os.path.join(with_plane, "metrics.prom")).read()
    assert "srnn_soup_alerts_active 0" in prom
    assert "srnn_soup_alerts" not in open(
        os.path.join(without, "metrics.prom")).read()


def test_no_export_bitwise_ab_mega_multisoup(tmp_path):
    from srnn_tpu.experiment import restore_multi_checkpoint

    with_plane = REGISTRY["mega_multisoup"](
        ["--smoke", "--seed", "47", "--root", str(tmp_path / "a")])
    without = REGISTRY["mega_multisoup"](
        ["--smoke", "--seed", "47", "--no-export",
         "--root", str(tmp_path / "b")])
    a = restore_multi_checkpoint(os.path.join(with_plane,
                                              "ckpt-gen00000006"))
    b = restore_multi_checkpoint(os.path.join(without,
                                              "ckpt-gen00000006"))
    for wa, wb in zip(a.weights, b.weights):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    import jax

    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(a.key)),
        np.asarray(jax.random.key_data(b.key)))
    assert os.path.exists(os.path.join(with_plane,
                                       "metrics_history.jsonl"))
    assert not os.path.exists(os.path.join(without,
                                           "metrics_history.jsonl"))


# ---------------------------------------------------------------------------
# watch integration: --url shares the --once render path
# ---------------------------------------------------------------------------


def test_watch_url_mode_and_precedence(tmp_path, capsys):
    from srnn_tpu.telemetry import watch

    reg = MetricsRegistry()
    reg.gauge("heartbeat_generation", help="g").set(42, stage="t")
    with MetricsExporter(
            reg, port=0,
            healthz=lambda: {"ok": True, "stage": "t",
                             "active_alerts": [
                                 {"rule": "soup_nan_frac", "value": 0.9,
                                  "for_s": 1.0}]}) as ex:
        # --once: machine-readable snapshot carrying the live block
        assert watch.main(["--url", ex.url, "--once"]) == 0
        snap = json.loads(capsys.readouterr().out)
        live = snap["live"]
        assert live["healthz"]["ok"] is True
        assert 'srnn_heartbeat_generation{stage="t"}' in live["metrics"]
        # run_dir + --url in one invocation: both blocks present (the
        # URL block is the liveness authority; docstring precedence)
        run_dir = str(tmp_path)
        open(os.path.join(run_dir, "events.jsonl"), "w").write(
            json.dumps({"kind": "alert", "rule": "r1",
                        "state": "firing", "t": 1.0}) + "\n")
        assert watch.main([run_dir, "--url", ex.url, "--once"]) == 0
        snap = json.loads(capsys.readouterr().out)
        assert "live" in snap and snap["alerts"]["active"] == ["r1"]
        # render paths: render_url + render share the refresh loop's
        # formatting helpers
        watch.render_url(live, __import__("io").StringIO())


def test_watch_snapshot_alert_panel_last_state_wins(tmp_path):
    from srnn_tpu.telemetry.watch import snapshot

    run_dir = str(tmp_path)
    with open(os.path.join(run_dir, "events.jsonl"), "w") as f:
        for row in ({"kind": "alert", "rule": "a", "state": "firing"},
                    {"kind": "alert", "rule": "a", "state": "cleared"},
                    {"kind": "alert", "rule": "b", "state": "firing"}):
            f.write(json.dumps(dict(row, t=1.0)) + "\n")
    s = snapshot(run_dir)
    assert s["alerts"] == {"fired": 2, "active": ["b"]}


def test_report_renders_history_and_alerts(tmp_path, capsys):
    """The report CLI folds the history stream and alert trail of a live
    run dir (synthesized here; the mega A/B test above produces the real
    thing)."""
    from srnn_tpu.telemetry.report import main

    run_dir = str(tmp_path)
    with open(os.path.join(run_dir, "events.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "alert", "rule": "soup_nan_frac",
                            "state": "firing", "value": 0.9, "t": 2.0})
                + "\n")
    with open(os.path.join(run_dir, "metrics_history.jsonl"), "w") as f:
        for i in range(4):
            f.write(json.dumps(
                {"kind": "metrics_history", "t": float(i),
                 "metrics": {"srnn_gens_per_sec{stage=\"s\"}": 10.0 + i,
                             "srnn_soup_generations_total": 2 * i}}) + "\n")
    assert main([run_dir]) == 0
    out = capsys.readouterr().out
    assert "history (4 samples" in out
    assert "gens_per_sec" in out
    assert "soup_nan_frac: fired 1x" in out and "last state firing" in out
    assert main([run_dir, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["alerts"]["by_rule"]["soup_nan_frac"]["fired"] == 1
    assert doc["history"]["series"]["soup_generations_total"][
        "rate_per_s"] == pytest.approx(2.0)
