"""Statistical parity with the reference's committed 2019 result logs
(BASELINE.md; SURVEY §4 implication (d)).

Exact RNG parity with tf.keras-era runs is impossible (different init
streams), so these tests check that class-count distributions at matched
configs land within generous sampling tolerance of the reference logs.
Tolerances are ±4σ of the implied binomial, so false failures are ~1e-4
rare while real behavioral drifts (e.g. a broken transform flipping
divergence rates) trip immediately.
"""

import jax
import numpy as np
import pytest

from srnn_tpu import Topology, init_population, run_fixpoint
from srnn_tpu.engine import run_known_fixpoint_variation
from srnn_tpu.fixtures import identity_fixpoint_flat, vary
from srnn_tpu.soup import SoupConfig, count, evolve, seed

TRIALS = 50


def _binomial_band(expected: int, n: int = TRIALS, sigmas: float = 4.0):
    p = expected / n
    sd = np.sqrt(n * p * (1 - p)) + 1e-9
    return max(0, expected - sigmas * sd - 1), min(n, expected + sigmas * sd + 1)


# reference: results/exp-applying_fixpoint-.../log.txt (BASELINE.md):
#   WW 23 divergent / 27 fix_zero; Agg 4 / 46; RNN 46 / 4
APPLYING_EXPECTED = {
    "weightwise": (23, 27),
    "aggregating": (4, 46),
    "recurrent": (46, 4),
}


@pytest.mark.parametrize("variant", sorted(APPLYING_EXPECTED))
def test_applying_fixpoints_distribution(variant):
    exp_div, exp_zero = APPLYING_EXPECTED[variant]
    topo = Topology(variant, width=2, depth=2)
    pop = init_population(topo, jax.random.key(42), TRIALS)
    res = run_fixpoint(topo, pop, step_limit=100)
    counts = np.asarray(res.counts)
    lo, hi = _binomial_band(exp_div)
    assert lo <= counts[0] <= hi, f"{variant} divergent {counts[0]} not in [{lo:.0f},{hi:.0f}]"
    lo, hi = _binomial_band(exp_zero)
    assert lo <= counts[1] <= hi, f"{variant} fix_zero {counts[1]} not in [{lo:.0f},{hi:.0f}]"
    # the reference observed only divergent/zero outcomes in this experiment
    assert counts[0] + counts[1] >= TRIALS - 3


def test_known_fixpoint_variation_curve():
    """Qualitative reproduction of the robustness curve (BASELINE.md row:
    3.63 steps to vergence at scale 1e0 rising toward ~26 at 1e-9, time as
    fixpoint 0 at 1e0 rising toward ~16)."""
    topo = Topology("weightwise", width=2, depth=2)
    fp = identity_fixpoint_flat(topo)
    trials = 32
    means_y, means_z = [], []
    scale = 1.0
    for level in range(10):
        keys = jax.random.split(jax.random.fold_in(jax.random.key(7), level), trials)
        pop = jax.vmap(lambda k: vary(k, fp, scale))(keys)
        res = run_known_fixpoint_variation(topo, pop, max_steps=100)
        means_y.append(float(np.mean(np.asarray(res.time_to_vergence))))
        means_z.append(float(np.mean(np.asarray(res.time_as_fixpoint))))
        scale /= 10.0
    # big perturbations verge fast and are never fixpoints
    assert means_y[0] < 10 and means_z[0] < 1
    # tiny perturbations survive much longer, much of it as a fixpoint
    assert means_y[-1] > 15 and means_z[-1] > 5
    # both curves rise (weakly) from coarse to fine scales overall
    assert means_y[-1] > means_y[0] and means_z[-1] > means_z[0]


def test_soup_trajectory_endstate():
    """BASELINE.md: Soup(20, train=30, attack 0.1, 100 gens) ends with 13
    fix_other / 7 other, 0 divergent, 0 zero.  Check the robust invariants:
    nobody dead, a majority trained into non-zero fixpoints."""
    topo = Topology("weightwise", width=2, depth=2)
    cfg = SoupConfig(topo=topo, size=20, attacking_rate=0.1,
                     learn_from_rate=-1.0, train=30,
                     remove_divergent=True, remove_zero=True)
    state = evolve(cfg, seed(cfg, jax.random.key(0)), generations=100)
    counts = np.asarray(count(cfg, state))
    assert counts[0] == 0 and counts[1] == 0      # respawn keeps soup alive
    assert counts[2] >= 10                         # majority fix_other
    assert counts.sum() == 20
